"""HTTP proxy: per-node ingress routing requests to deployment handles.

Capability parity: reference python/ray/serve/_private/proxy.py (HTTPProxy :699,
ProxyActor :1021) — route-prefix matching, JSON request/response bridging to handles.
aiohttp replaces uvicorn (not baked into this image); the blocking handle call runs on
an executor thread so the event loop keeps accepting connections.
"""
from __future__ import annotations

import asyncio
import concurrent.futures
import json
import threading
import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.core.exceptions import BackPressureError
from ray_tpu.serve.handle import StreamHandoff
from ray_tpu.util import telemetry

from .controller import CONTROLLER_NAME
from .handle import DeploymentHandle


def _observe_ttft(route: str, seconds: float) -> None:
    """Time-to-first-byte at the ingress: first stream chunk for SSE requests,
    the full response for unary ones — the p50/p99 rows in `ray-tpu status`
    and the SLO input for autoscaling."""
    telemetry.get_histogram(
        "serve_ttft_seconds", "HTTP ingress time-to-first-token/response",
        tag_keys=("route",)).observe(seconds, tags={"route": route})


class ProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        self._handles: Dict[str, DeploymentHandle] = {}
        self._routes: Dict[str, Dict[str, Any]] = {}
        self._hint_cache = (0.0, None)  # (fetched_at, windowed p50 or None)
        self._ready = threading.Event()
        self._thread = threading.Thread(target=self._serve_forever, daemon=True,
                                        name="serve-http-proxy")
        self._thread.start()

    def ready(self) -> bool:
        self._ready.wait(timeout=30)
        return self._ready.is_set()

    def _retry_after_s(self, fallback: float) -> int:
        """Retry-After for shed responses, derived from the head's WINDOWED
        request-latency history (the recent regime: one service time ~= how
        long until a replica slot frees) — the handle's EWMA is the fallback
        when no history is retained yet. Cached 5s so a shed storm costs one
        state RPC per window, not one per 503."""
        import math

        from .handle import retry_after_from_latency

        now = time.monotonic()
        ts, p50 = self._hint_cache
        if now - ts > 5.0:
            p50 = None
            try:
                from ray_tpu.util.state import serve_latency_hint

                p50 = serve_latency_hint().get("serve_request_p50_s")
            # graftlint: allow[swallowed-exception] no metrics history yet: Retry-After keeps the static fallback
            except Exception:  # noqa: BLE001 — no history/scraper: use fallback
                pass
            self._hint_cache = (now, p50)
        return max(1, int(math.ceil(retry_after_from_latency(p50, fallback))))

    def _shed_response(self, web, e: BackPressureError):
        # the handle's _maybe_shed already counted serve_requests_shed_total;
        # the proxy's job is the wire protocol: 503 + Retry-After
        return web.Response(
            status=503, text=str(e),
            headers={"Retry-After": str(self._retry_after_s(e.retry_after_s))})

    def _refresh_routes(self) -> None:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        self._routes = ray_tpu.get(controller.get_routing_table.remote())

    def _match(self, path: str):
        best = None
        for prefix, info in self._routes.items():
            if path == prefix or path.startswith(prefix.rstrip("/") + "/") or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, info)
        return best

    def _serve_forever(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def handler(request: "web.Request") -> "web.Response":
            t0_wall, t0_perf = time.time_ns(), time.perf_counter_ns()
            self._refresh_routes()
            m = self._match(request.path)
            if m is None:
                return web.Response(status=404, text=f"no route for {request.path}")
            prefix, info = m

            # -- request-scoped tracing (W3C traceparent in / out) ---------
            # an incoming traceparent enables tracing for THIS request only:
            # the context set under _in_ctx is itself the enable signal
            # (tracing.is_tracing_enabled honors an active context), so one
            # unauthenticated probe cannot flip a process-wide switch. With
            # tracing globally on, requests without a header root a fresh
            # trace. The proxy span's id becomes the parent the handle->
            # replica->engine chain inherits, so state.request_trace(trace_id)
            # sees one tree spanning proxy and replica processes.
            from ray_tpu.util import tracing

            incoming = tracing.parse_traceparent(
                request.headers.get("traceparent"))
            traced = incoming is not None or tracing.is_tracing_enabled()
            if traced:
                import uuid as _uuid

                trace_id = (incoming["trace_id"] if incoming
                            else _uuid.uuid4().hex)
                upstream_parent = incoming["parent_span_id"] if incoming else ""
                proxy_span_id = tracing.new_span_id()
                child_ctx = {"trace_id": trace_id,
                             "parent_span_id": proxy_span_id}
                traceparent_out = tracing.format_traceparent(
                    trace_id, proxy_span_id)
            else:
                child_ctx = traceparent_out = None

            span_fired = []

            def _finish_span(stream: bool, status: int) -> None:
                # once-only: the streaming path also fires from its finally
                # so a client disconnect mid-stream still records the root
                # span (those aborted requests are the ones worth tracing)
                if not traced or span_fired:
                    return
                span_fired.append(True)
                end_wall_ns = t0_wall + (time.perf_counter_ns() - t0_perf)
                tracing.record_complete_span(
                    "serve.http", t0_wall / 1e9, end_wall_ns / 1e9,
                    trace_id, proxy_span_id, upstream_parent,
                    {"route": prefix, "method": request.method,
                     "path": request.path, "stream": stream,
                     "status": status})

            def _in_ctx(fn):
                """Run fn under the request's trace context and restore the
                (pooled) executor thread afterwards — a leaked contextvar
                would stitch unrelated requests into this trace."""
                if child_ctx is None:
                    return fn

                def wrapped(*a, **kw):
                    token = tracing.set_trace_context(child_ctx)
                    try:
                        return fn(*a, **kw)
                    finally:
                        tracing._ctx.reset(token)
                return wrapped

            def _respond(resp, stream: bool):
                """Close the ingress span and echo the traceparent so callers
                (and tests) learn the trace id to hand request_trace()."""
                if traced:
                    try:
                        resp.headers["traceparent"] = traceparent_out
                    # graftlint: allow[swallowed-exception] response already streaming: headers immutable, trace header is best-effort
                    except Exception:  # noqa: BLE001 — already-prepared stream
                        pass
                    _finish_span(stream, getattr(resp, "status", 200))
                return resp
            key = f"{info['app']}/{info['deployment']}"
            if key not in self._handles:
                self._handles[key] = DeploymentHandle(info["app"], info["deployment"])
            handle = self._handles[key]
            if request.can_read_body:
                try:
                    payload = await request.json()
                except json.JSONDecodeError:
                    payload = (await request.read()).decode()
            else:
                payload = dict(request.query)

            request_dict = {
                "path": request.path[len(prefix.rstrip("/")):] or "/",
                "method": request.method,
                "query": dict(request.query),
                "headers": dict(request.headers),
                "body": payload,
            }

            # streaming (reference proxy.py:699 ASGI streaming): OpenAI-style
            # {"stream": true} bodies or ?stream=1 run a streaming handle call
            # and forward chunks as they arrive (SSE-compatible)
            # truthiness, matching OpenAIRouter's gate — {"stream": 1} must not
            # desynchronize the proxy (non-stream) from the router (stream)
            wants_stream = (
                (isinstance(payload, dict) and bool(payload.get("stream")))
                or request.query.get("stream") in ("1", "true")
            )
            if wants_stream:
                # handle.remote() blocks on replica discovery (up to 30s) and
                # every next(g) blocks until the replica yields. Each stream
                # gets its OWN single-thread executor: a handful of slow or
                # idle streaming clients must not occupy the event loop's
                # default executor (min(32, cpus+4) threads — ~5 on a small
                # host), which also serves every non-streaming call.
                stream_exec = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="serve-sse")

                def start_stream():
                    return handle.options(method_name="__http__",
                                          stream=True).remote(request_dict)

                _end = object()

                def make_pull(g):
                    def pull():
                        try:
                            return next(g)
                        except StopIteration:
                            return _end
                    return pull

                gen = None
                try:
                    try:
                        gen = await loop.run_in_executor(
                            stream_exec, _in_ctx(start_stream))
                        pull = make_pull(gen)
                        first = await loop.run_in_executor(stream_exec, pull)
                        _observe_ttft(prefix,
                                      (time.perf_counter_ns() - t0_perf) / 1e9)
                        # "stream": true is an OpenAI convention; a deployment
                        # that returned one plain JSON value was not actually
                        # streaming — answer with ordinary JSON instead of a
                        # one-blob SSE body
                        if isinstance(first, (dict, list)):
                            second = await loop.run_in_executor(stream_exec, pull)
                            if second is _end:
                                return _respond(web.json_response(first),
                                                stream=False)
                            pending = [first, second]
                        else:
                            pending = [] if first is _end else [first]
                    except BackPressureError as e:
                        # shed before the stream started: fast 503 + Retry-After
                        return _respond(self._shed_response(web, e),
                                        stream=True)
                    except Exception as e:  # noqa: BLE001 - surface as 500
                        return _respond(web.Response(status=500, text=repr(e)),
                                        stream=True)
                    hdrs = {"Content-Type": "text/event-stream",
                            "Cache-Control": "no-cache"}
                    if traced:  # StreamResponse headers are fixed at prepare()
                        hdrs["traceparent"] = traceparent_out
                    resp = web.StreamResponse(headers=hdrs)
                    await resp.prepare(request)

                    async def write_chunk(chunk):
                        if isinstance(chunk, bytes):
                            await resp.write(chunk)
                        elif isinstance(chunk, str):
                            await resp.write(chunk.encode())
                        else:
                            await resp.write(json.dumps(chunk).encode() + b"\n")

                    handoff = None  # upstream stream adopted from a relay
                    try:
                        for chunk in pending:
                            if isinstance(chunk, StreamHandoff):
                                handoff = chunk.resume()
                                pull = make_pull(handoff)
                            else:
                                await write_chunk(chunk)
                        while True:
                            chunk = await loop.run_in_executor(stream_exec, pull)
                            if chunk is _end:
                                break
                            if isinstance(chunk, StreamHandoff):
                                # a relay deployment (P/D router) handed us its
                                # upstream mid-stream: drain the producing
                                # replica directly, skipping the relay's
                                # per-chunk re-put for the rest of the body
                                handoff = chunk.resume()
                                pull = make_pull(handoff)
                                continue
                            await write_chunk(chunk)
                    except Exception as e:  # noqa: BLE001 — mid-stream: terminate body
                        # client gone or replica error: stop the producer so it
                        # releases engine resources (KV slots) early
                        if gen is not None:
                            stream_exec.submit(gen.close)
                            gen = None
                        if handoff is not None:
                            stream_exec.submit(handoff.close)
                            handoff = None
                        try:
                            await resp.write(f"\nerror: {e!r}\n".encode())
                        # graftlint: allow[swallowed-exception] client socket already closed while reporting a stream error
                        except Exception:  # noqa: BLE001 — socket already closed
                            pass
                    await resp.write_eof()
                    if telemetry.enabled():
                        telemetry.complete(
                            "serve.http", "serve", t0_wall,
                            time.perf_counter_ns() - t0_perf,
                            route=prefix, method=request.method, stream=True,
                            trace_id=trace_id if traced else None)
                    _finish_span(True, 200)
                    return resp
                finally:
                    # covers abrupt exits (client disconnect raising out of
                    # prepare/write, task cancellation): the ingress span is
                    # recorded exactly once either way
                    _finish_span(True, 499)
                    if gen is not None:
                        stream_exec.submit(gen.close)
                    stream_exec.shutdown(wait=False)

            def call():
                return handle.options(method_name="__http__").remote(request_dict).result()

            try:
                result = await loop.run_in_executor(None, _in_ctx(call))
            except BackPressureError as e:
                # admission control tripped: degrade to a FAST rejection the
                # client can back off on, not a queued request that times out
                return _respond(self._shed_response(web, e), stream=False)
            except Exception as e:  # noqa: BLE001 - surface as 500
                return _respond(web.Response(status=500, text=repr(e)),
                                stream=False)
            _observe_ttft(prefix, (time.perf_counter_ns() - t0_perf) / 1e9)
            if telemetry.enabled():
                telemetry.complete(
                    "serve.http", "serve", t0_wall,
                    time.perf_counter_ns() - t0_perf,
                    route=prefix, method=request.method, stream=False,
                    trace_id=trace_id if traced else None)
            from .asgi import RAW_RESPONSE_KEY

            if isinstance(result, dict) and result.get(RAW_RESPONSE_KEY):
                # ASGI deployments return verbatim status/headers/body; repeated
                # header names (multiple Set-Cookie) must survive, so build a
                # multidict rather than a plain dict
                from multidict import CIMultiDict

                hdrs = CIMultiDict()
                for k, v in result["headers"]:
                    if k.lower() != "content-length":
                        hdrs.add(k, v)
                return _respond(web.Response(status=result["status"],
                                             body=result["body"], headers=hdrs),
                                stream=False)
            if isinstance(result, (dict, list)):
                return _respond(web.json_response(result), stream=False)
            if isinstance(result, bytes):
                return _respond(web.Response(body=result), stream=False)
            return _respond(web.Response(text=str(result)), stream=False)

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handler)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        ssl_ctx = None
        from ray_tpu.config import CONFIG

        if CONFIG.serve_ingress_tls:
            from ray_tpu.core.tls_utils import ingress_ssl_context

            ssl_ctx = ingress_ssl_context()
        site = web.TCPSite(runner, self.host, self.port, ssl_context=ssl_ctx)
        loop.run_until_complete(site.start())
        self._ready.set()
        loop.run_forever()

    def stop(self) -> None:
        pass
