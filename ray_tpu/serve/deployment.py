"""@serve.deployment decorator, Deployment, Application (bind graph).

Capability parity: reference python/ray/serve/api.py:322 (@deployment), deployment.py
(Deployment.options/bind), and the DAG-lite Application model: bound deployments with
constructor args; nested bound deployments become DeploymentHandles at replica init.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from .config import AutoscalingConfig, DeploymentConfig


@dataclasses.dataclass
class Application:
    """A bound deployment graph; pass to serve.run()."""

    deployment: "Deployment"
    args: Tuple
    kwargs: Dict[str, Any]

    def _collect(self, out: List["Application"]) -> None:
        for a in list(self.args) + list(self.kwargs.values()):
            if isinstance(a, Application):
                a._collect(out)
        if all(x.deployment.name != self.deployment.name for x in out):
            out.append(self)


class Deployment:
    def __init__(self, target: Union[type, Callable], name: str, config: DeploymentConfig):
        self._target = target
        self.name = name
        self.config = config

    def options(
        self,
        *,
        name: Optional[str] = None,
        num_replicas: Optional[Union[int, str]] = None,
        max_ongoing_requests: Optional[int] = None,
        max_queued_requests: Optional[int] = None,
        retryable: Optional[bool] = None,
        drain_timeout_s: Optional[float] = None,
        autoscaling_config: Optional[Union[AutoscalingConfig, Dict]] = None,
        ray_actor_options: Optional[Dict[str, Any]] = None,
        user_config: Optional[Dict[str, Any]] = None,
        version: Optional[str] = None,
        health_check_period_s: Optional[float] = None,
        placement_strategy: Optional[str] = None,
        **_compat,
    ) -> "Deployment":
        cfg = dataclasses.replace(self.config)
        if isinstance(num_replicas, str) and num_replicas == "auto":
            autoscaling_config = autoscaling_config or AutoscalingConfig()
            num_replicas = None
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if max_queued_requests is not None:
            cfg.max_queued_requests = max_queued_requests
        if retryable is not None:
            cfg.retryable = retryable
        if drain_timeout_s is not None:
            cfg.drain_timeout_s = drain_timeout_s
        if autoscaling_config is not None:
            if isinstance(autoscaling_config, dict):
                autoscaling_config = AutoscalingConfig(**autoscaling_config)
            cfg.autoscaling_config = autoscaling_config
            cfg.num_replicas = None
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        if user_config is not None:
            cfg.user_config = dict(user_config)
        if version is not None:
            cfg.version = version
        if health_check_period_s is not None:
            cfg.health_check_period_s = health_check_period_s
        if placement_strategy is not None:
            if placement_strategy not in ("PACK", "SPREAD"):
                raise ValueError("placement_strategy must be PACK or SPREAD")
            cfg.placement_strategy = placement_strategy
        return Deployment(self._target, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def __repr__(self):
        return f"Deployment({self.name})"


def deployment(
    _target: Optional[Union[type, Callable]] = None,
    *,
    name: Optional[str] = None,
    num_replicas: Optional[Union[int, str]] = None,
    max_ongoing_requests: int = 8,
    max_queued_requests: Optional[int] = None,
    retryable: bool = True,
    drain_timeout_s: Optional[float] = None,
    autoscaling_config: Optional[Union[AutoscalingConfig, Dict]] = None,
    ray_actor_options: Optional[Dict[str, Any]] = None,
    user_config: Optional[Dict[str, Any]] = None,
    version: Optional[str] = None,
    health_check_period_s: float = 5.0,
    placement_strategy: str = "PACK",
    **_compat,
):
    """@serve.deployment (reference api.py:322)."""

    def wrap(target):
        if placement_strategy not in ("PACK", "SPREAD"):
            raise ValueError("placement_strategy must be PACK or SPREAD")
        cfg = DeploymentConfig(
            num_replicas=1,
            max_ongoing_requests=max_ongoing_requests,
            retryable=retryable,
            ray_actor_options=ray_actor_options or {},
            user_config=user_config,
            version=version,
            health_check_period_s=health_check_period_s,
            placement_strategy=placement_strategy,
        )
        if max_queued_requests is not None:
            cfg.max_queued_requests = max_queued_requests
        if drain_timeout_s is not None:
            cfg.drain_timeout_s = drain_timeout_s
        d = Deployment(target, name or getattr(target, "__name__", "deployment"), cfg)
        if num_replicas is not None or autoscaling_config is not None:
            d = d.options(num_replicas=num_replicas, autoscaling_config=autoscaling_config)
        return d

    if _target is not None:
        return wrap(_target)
    return wrap
