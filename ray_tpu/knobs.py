"""Central knob registry: every RAY_TPU_* environment knob, in one place.

THE single source of truth for the project's environment knobs (name, type,
default, one-line doc, owning subsystem). `ray_tpu.config` builds its CONFIG
flag table from the entries that carry an `attr` (the operator-facing flags);
entries without one are read directly from the environment at their use site
(module-level tunables like the grad-sync worker knobs) or are `internal=True`
worker-plumbing protocol variables the runtime sets for its own children
(RAY_TPU_ARENA, RAY_TPU_TRAIN_RANK, ...).

Invariants, machine-checked by graftlint (`ray-tpu lint`, check knob-registry):

- every `RAY_TPU_*` string the codebase reads from the environment is
  registered here (unregistered reads are lint violations);
- every non-internal entry is still referenced somewhere (stale entries are
  lint violations);
- the README knob tables are GENERATED from this registry
  (`ray-tpu lint --write-docs`); hand-edits between the markers are drift and
  fail lint.

This module must stay stdlib-only: graftlint loads it while analyzing the
tree, and the analyzer guarantees it never pulls in jax or the runtime.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class Knob:
    env: str  # environment variable name
    type: str  # "int" | "float" | "bool" | "str"
    default: Any  # None = unset/auto
    doc: str  # one-line operator-facing description
    subsystem: str  # owning subsystem (one README table per subsystem)
    attr: Optional[str] = None  # ray_tpu.config.CONFIG attribute, if any
    internal: bool = False  # worker-plumbing protocol, not an operator flag


KNOBS: List[Knob] = [
    # -- core
    Knob("RAY_TPU_NUM_CPUS", "float", None,
         "CPU capacity this node advertises (default: os.cpu_count()).",
         "core", attr="num_cpus"),
    Knob("RAY_TPU_NUM_TPUS", "float", None,
         "TPU chip capacity this node advertises (default: auto-detect).",
         "core", attr="num_tpus"),
    Knob("RAY_TPU_MAX_WORKERS_PER_NODE", "int", 16,
         "Worker-process cap per node (reference: raylet worker pool size).",
         "core", attr="max_workers_per_node"),
    Knob("RAY_TPU_TASK_MAX_RETRIES", "int", 3,
         "Default max_retries for @remote tasks when unspecified "
         "(reference task_max_retries / TASK_MAX_RETRIES default).",
         "core", attr="task_max_retries"),
    Knob("RAY_TPU_ACTOR_MAX_RESTARTS", "int", 0,
         "Default max_restarts for actors when unspecified (reference "
         "actor restart semantics: 0 = never restart).",
         "core", attr="actor_max_restarts"),
    Knob("RAY_TPU_WORKER_START_TIMEOUT_S", "float", 60.0,
         "How long the pool waits for a spawned worker's handshake "
         "(reference worker_register_timeout_seconds).",
         "core", attr="worker_start_timeout_s"),
    # -- object-store
    Knob("RAY_TPU_OBJECT_STORE_BYTES", "int", 512 * 1024 * 1024,
         "Shared-memory arena capacity per node (plasma-equivalent).",
         "object-store", attr="object_store_bytes"),
    Knob("RAY_TPU_SPILL_DIR", "str", "/tmp",
         "Directory for objects spilled from shared memory to disk.",
         "object-store", attr="spill_dir"),
    Knob("RAY_TPU_SPILL_THRESHOLD", "float", 0.8,
         "Arena-usage fraction above which LRU spilling starts.",
         "object-store", attr="spill_threshold"),
    Knob("RAY_TPU_SPILL_TARGET", "float", 0.5,
         "Arena-usage fraction spilling drives down to.",
         "object-store", attr="spill_target"),
    Knob("RAY_TPU_MEMORY_USAGE_THRESHOLD", "float", 0.95,
         "System-memory fraction that triggers the OOM worker killer "
         "(reference memory_monitor.h).",
         "object-store", attr="memory_usage_threshold"),
    Knob("RAY_TPU_MEMORY_MONITOR_REFRESH_MS", "int", 250,
         "Memory monitor / spill check period.",
         "object-store", attr="memory_monitor_refresh_ms"),
    Knob("RAY_TPU_INLINE_THRESHOLD_BYTES", "int", 100 * 1024,
         "Objects below this travel inline in control messages instead of the "
         "arena (reference max_direct_call_object_size).",
         "object-store", attr="inline_threshold_bytes"),
    Knob("RAY_TPU_OOB_THRESHOLD_BYTES", "int", 1 << 16,
         "Pickle buffers at or above this serialize out-of-band (zero-copy "
         "into the arena) instead of inline in the pickle stream.",
         "object-store", attr="oob_threshold_bytes"),
    Knob("RAY_TPU_OBJECT_LOCATION_TIMEOUT_S", "float", 60.0,
         "How long a get() waits for a recovering object's new location "
         "after lineage resubmission before failing.",
         "object-store", attr="object_location_timeout_s"),
    Knob("RAY_TPU_LOCALIZE_PULL_TIMEOUT_S", "float", 120.0,
         "Deadline for pulling a task's missing arguments to its assigned "
         "node; expiry triggers lineage reconstruction or task failure.",
         "object-store", attr="localize_pull_timeout_s"),
    # -- transfer
    Knob("RAY_TPU_TRANSFER_CHUNK_BYTES", "int", 4 * 1024 * 1024,
         "Chunk size for direct node-to-node object transfers "
         "(reference push_manager.h chunked push).",
         "transfer", attr="transfer_chunk_bytes"),
    Knob("RAY_TPU_TRANSFER_INFLIGHT_BYTES", "int", 256 * 1024 * 1024,
         "Per-node byte budget for concurrent incoming object pulls "
         "(reference pull_manager.h admission control).",
         "transfer", attr="transfer_inflight_bytes"),
    Knob("RAY_TPU_TRANSFER_MAX_PULLS", "int", 8,
         "Max concurrent pulls a node issues (and streams it serves).",
         "transfer", attr="transfer_max_pulls"),
    Knob("RAY_TPU_TRANSFER_UDS", "bool", True,
         "Same-host data-plane pulls ride an abstract unix socket instead of "
         "loopback TCP (~1.4x bulk throughput); remote pulls and TLS mode "
         "always use TCP. The authkey challenge gates both transports.",
         "transfer", attr="transfer_uds"),
    Knob("RAY_TPU_TRANSFER_STRIPE_THRESHOLD_BYTES", "int", 8 * 1024 * 1024,
         "Objects at or above this size pull as concurrent byte-range stripes "
         "over pooled connections (0 disables striping). All stripes of one "
         "pull share a single admission grant.",
         "transfer", attr="transfer_stripe_threshold_bytes"),
    Knob("RAY_TPU_TRANSFER_STRIPES", "int", 4,
         "Max concurrent range streams per striped pull.",
         "transfer", attr="transfer_stripes"),
    Knob("RAY_TPU_TRANSFER_STRIPE_MIN_BYTES", "int", 2 * 1024 * 1024,
         "Never split a pull so finely that a stripe falls below this many "
         "bytes (each stripe pays a request/admission handshake).",
         "transfer", attr="transfer_stripe_min_bytes"),
    Knob("RAY_TPU_TRANSFER_SAME_HOST_MAP", "bool", True,
         "When the source's shm/arena/spill location is directly readable "
         "from the pulling process (source shares this machine's /dev/shm — "
         "colocated node processes), map it in place instead of copying the "
         "bytes over loopback TCP (reference: one plasma store per node). "
         "The striped wire path is for genuinely-remote peers.",
         "transfer", attr="transfer_same_host_map"),
    Knob("RAY_TPU_TRANSFER_TIMEOUT_S", "float", 300.0,
         "Deadline for one direct object transfer before head-relay fallback.",
         "transfer", attr="transfer_timeout_s"),
    Knob("RAY_TPU_TRANSFER_STALL_TIMEOUT_S", "float", 60.0,
         "Per-socket-op stall bound on data-plane transfers (a half-dead peer "
         "must not pin admission slots / puller threads forever).",
         "transfer", attr="transfer_stall_timeout_s"),
    # -- device-plane
    Knob("RAY_TPU_DEVICE_PLANE", "bool", True,
         "Enable the PJRT transfer-server plane: jax.Arrays move between actor "
         "processes device-to-device (DCN/ICI on pods) instead of "
         "device->host->pickle (reference gpu_object_manager + NCCL channels).",
         "device-plane", attr="device_plane"),
    Knob("RAY_TPU_DEVICE_OBJECTS", "str", "fetch",
         "jax.Arrays in the object store: 'off' = host copy only; 'fetch' "
         "(default) = host copy kept, consumers pull device-to-device when "
         "possible; 'native' = stub only, device-resident at the producer "
         "(reference gpu_object_manager semantics: loss -> reconstruction).",
         "device-plane", attr="device_objects"),
    Knob("RAY_TPU_DEVICE_OBJECT_MIN_BYTES", "int", 1 << 20,
         "Device arrays below this size skip the transfer plane (control-message "
         "inlining beats an arm round-trip for small tensors).",
         "device-plane", attr="device_object_min_bytes"),
    # -- collective
    Knob("RAY_TPU_COLLECTIVE_OP_TIMEOUT_S", "float", 30.0,
         "Host-plane collective op timeout (allreduce/broadcast/...); "
         "barriers wait 2x this.",
         "collective", attr="collective_op_timeout_s"),
    Knob("RAY_TPU_COLLECTIVE_ABORT_POLL_INTERVAL_S", "float", 0.25,
         "How often ring-path collective waits (stream reduce, gathers, tree "
         "relays) probe the group coordinator's abort poison flag: a dead "
         "rank costs survivors one interval, not collective_op_timeout_s.",
         "collective", attr="collective_abort_poll_interval_s"),
    Knob("RAY_TPU_COLLECTIVE_RING_THRESHOLD_BYTES", "int", 64 * 1024,
         "SHM-collective payloads at or above this size move peer-to-peer over "
         "the data plane (ring path, coordinator carries metadata only); "
         "smaller payloads ride the coordinator board directly.",
         "collective", attr="collective_ring_threshold_bytes"),
    Knob("RAY_TPU_COLLECTIVE_SERVER_STREAMS", "int", 64,
         "Concurrent serve streams on a rank's collective data-plane server. "
         "Ring reads block until the local chunk is published, so this is "
         "sized above transfer_max_pulls to keep blocked readers from "
         "starving live ones.",
         "collective", attr="collective_server_streams"),
    # -- control-plane
    Knob("RAY_TPU_AGENT_HEARTBEAT_S", "float", 2.0,
         "Node-agent heartbeat period to the head.",
         "control-plane", attr="agent_heartbeat_s"),
    Knob("RAY_TPU_AGENT_BATCH_MAX", "int", 128,
         "Max frames coalesced into one gRPC agent-stream message (batching "
         "packs only already-queued frames: zero added latency).",
         "control-plane", attr="agent_batch_max"),
    Knob("RAY_TPU_AGENT_QUEUE_DEPTH", "int", 4096,
         "Outbound frame buffer per agent stream; a stalled peer exerts "
         "backpressure once full instead of accumulating frames in RAM.",
         "control-plane", attr="agent_queue_depth"),
    Knob("RAY_TPU_AGENT_SEND_TIMEOUT_S", "float", 30.0,
         "How long send() blocks on a backed-up agent stream before raising.",
         "control-plane", attr="agent_send_timeout_s"),
    Knob("RAY_TPU_AGENT_HEARTBEAT_TIMEOUT_S", "float", 10.0,
         "Head marks an agent dead after this long without a heartbeat "
         "(reference gcs_health_check_manager.h).",
         "control-plane", attr="agent_heartbeat_timeout_s"),
    Knob("RAY_TPU_AGENT_RECONNECT_TIMEOUT_S", "float", 60.0,
         "How long a node agent keeps its workers alive while redialing a "
         "restarted head before giving up (reference: raylets buffering "
         "through a GCS restart, NotifyGCSRestart).",
         "control-plane", attr="agent_reconnect_timeout_s"),
    Knob("RAY_TPU_HEAD_RECONNECT_TIMEOUT_S", "float", 30.0,
         "How long a driver/worker control context redials an unreachable "
         "head (jittered backoff) before failing head-requiring calls with "
         "HeadUnavailableError.",
         "control-plane", attr="head_reconnect_timeout_s"),
    Knob("RAY_TPU_HEAD_RECONNECT_BACKOFF_S", "float", 0.25,
         "Initial redial backoff for a lost head connection; doubles per "
         "attempt with jitter.",
         "control-plane", attr="head_reconnect_backoff_s"),
    Knob("RAY_TPU_HEAD_RECONNECT_BACKOFF_MAX_S", "float", 3.0,
         "Redial backoff ceiling for a lost head connection.",
         "control-plane", attr="head_reconnect_backoff_max_s"),
    Knob("RAY_TPU_HEAD_OUTBOX_LIMIT", "int", 4096,
         "Max loss-intolerant control messages (decref/kill/drop_stream, "
         "agent relay frames) buffered for sequence-numbered replay across a "
         "head outage; beyond it the oldest are dropped with a warning.",
         "control-plane", attr="head_outbox_limit"),
    Knob("RAY_TPU_HEAD_RESTART_GRACE_S", "float", 30.0,
         "Reaper grace window after head boot: agents that were healthy "
         "through a head outage get this long to reattach before the "
         "heartbeat reaper may declare them dead.",
         "control-plane", attr="head_restart_grace_s"),
    Knob("RAY_TPU_SESSION_DIR", "str", "/tmp/ray_tpu_session",
         "Session directory (head metadata, jobs, authkey, usage report).",
         "control-plane", attr="session_dir"),
    Knob("RAY_TPU_CLIENT_AUTHKEY", "str", None,
         "Cluster authkey for remote drivers/agents (default: generated and "
         "persisted in the session dir).",
         "control-plane", attr="client_authkey"),
    Knob("RAY_TPU_GCS_PERSISTENCE_PATH", "str", None,
         "Journal file for GCS KV persistence across restarts (default: off).",
         "control-plane", attr="gcs_persistence_path"),
    Knob("RAY_TPU_GCS_OWNER_CHECK_EVERY", "int", 32,
         "URI-journal split-brain fencing: re-verify lease ownership every N "
         "appends (lower = faster usurper detection, more object reads).",
         "control-plane", attr="gcs_owner_check_every"),
    # -- security
    Knob("RAY_TPU_TLS_HANDSHAKE_TIMEOUT_S", "float", 15.0,
         "Deferred server-side TLS handshake deadline per connection.",
         "security", attr="tls_handshake_timeout_s"),
    Knob("RAY_TPU_USE_TLS", "bool", False,
         "mTLS on the gRPC agent channel and the data/device-plane listeners; "
         "plaintext peers are refused (reference tls_utils.py RAY_USE_TLS).",
         "security", attr="use_tls"),
    Knob("RAY_TPU_TLS_CA", "str", None,
         "CA certificate path (both trust root and client-auth verifier).",
         "security", attr="tls_ca"),
    Knob("RAY_TPU_TLS_CERT", "str", None,
         "Cluster certificate path (`ray-tpu tls-init` mints one).",
         "security", attr="tls_cert"),
    Knob("RAY_TPU_TLS_KEY", "str", None,
         "Cluster private key path.",
         "security", attr="tls_key"),
    Knob("RAY_TPU_SERVE_INGRESS_TLS", "bool", False,
         "Serve the HTTP and gRPC ingress proxies over TLS using the cluster "
         "certificate (server-side TLS: external clients verify against "
         "ca.crt but need no client cert, unlike the inter-node mTLS planes).",
         "security", attr="serve_ingress_tls"),
    # -- runtime-env
    Knob("RAY_TPU_CONTAINER_RUNTIME", "str", None,
         "Container launcher binary for container/image_uri runtime envs "
         "(default: docker, then podman, from PATH). Point it at a recording "
         "stub to test invocations without a real runtime.",
         "runtime-env", attr="container_runtime"),
    # -- job
    Knob("RAY_TPU_JOB_STOP_GRACE_S", "float", 5.0,
         "SIGTERM-to-SIGKILL grace when stopping a submitted job's process "
         "group (reference: job stop_timeout).",
         "job", attr="job_stop_grace_s"),
    # -- dag
    Knob("RAY_TPU_DAG_CHANNEL_BUFFER_BYTES", "int", 4 * 1024 * 1024,
         "Default seqlock shm channel capacity for compiled DAGs "
         "(experimental_compile buffer_size_bytes; reference "
         "ChannelContext buffer sizing).",
         "dag", attr="dag_channel_buffer_bytes"),
    # -- data
    Knob("RAY_TPU_DATA_MAX_INFLIGHT_TASKS_PER_OP", "int", 8,
         "Streaming-executor backpressure: tasks in flight per operator "
         "(reference backpressure_policy concurrency caps).",
         "data", attr="data_max_inflight_tasks_per_op"),
    Knob("RAY_TPU_DATA_ACTOR_POOL_MAX_SIZE", "int", 4,
         "Default actor-pool size for map_batches(Class) stages.",
         "data", attr="data_actor_pool_max_size"),
    Knob("RAY_TPU_DATA_READ_OP_MIN_NUM_BLOCKS", "int", 8,
         "Default read parallelism when the datasource does not dictate one.",
         "data", attr="data_read_op_min_num_blocks"),
    Knob("RAY_TPU_DATA_TARGET_MAX_BLOCK_SIZE", "int", 128 * 1024 * 1024,
         "Blocks above this split on output (reference target_max_block_size).",
         "data", attr="data_target_max_block_size"),
    Knob("RAY_TPU_DATA_TARGET_MIN_BLOCK_SIZE", "int", 1 * 1024 * 1024,
         "Coalesce blocks below this (reference target_min_block_size).",
         "data", attr="data_target_min_block_size"),
    Knob("RAY_TPU_DATA_DEFAULT_BATCH_SIZE", "int", 1024,
         "map_batches/iter_batches batch size when unspecified.",
         "data", attr="data_default_batch_size"),
    Knob("RAY_TPU_DATA_OP_OUTPUT_BUFFER_LIMIT", "int", 16,
         "Streaming-executor per-operator output queue cap (backpressure).",
         "data", attr="data_op_output_buffer_limit"),
    Knob("RAY_TPU_DATA_PUSH_BASED_SHUFFLE", "bool", False,
         "Staged-merge shuffle for large sorts (reference "
         "push_based_shuffle_task_scheduler; RAY_DATA_PUSH_BASED_SHUFFLE).",
         "data", attr="data_push_based_shuffle"),
    Knob("RAY_TPU_DATA_PUSH_SHUFFLE_MERGE_FACTOR", "int", 8,
         "Map-round width for the push-based shuffle (fan-in bound).",
         "data", attr="data_push_shuffle_merge_factor"),
    # -- serve
    Knob("RAY_TPU_SERVE_RECONCILE_INTERVAL_S", "float", 0.2,
         "Serve controller reconciliation loop period (replica "
         "create/kill, health checks, autoscale decisions).",
         "serve", attr="serve_reconcile_interval_s"),
    Knob("RAY_TPU_SERVE_REPLICA_WAIT_S", "float", 30.0,
         "How long a handle call waits for a live replica before failing "
         "(reference handle resolution timeout).",
         "serve", attr="serve_replica_wait_s"),
    Knob("RAY_TPU_SERVE_HEALTH_CHECK_PERIOD_S", "float", 5.0,
         "Default replica health-check period (per-deployment override in "
         "DeploymentConfig; reference health_check_period_s).",
         "serve", attr="serve_health_check_period_s"),
    Knob("RAY_TPU_SERVE_HEALTH_CHECK_TIMEOUT_S", "float", 10.0,
         "Default grace before an unresponsive replica is replaced "
         "(reference health_check_timeout_s).",
         "serve", attr="serve_health_check_timeout_s"),
    Knob("RAY_TPU_SERVE_MAX_ONGOING_REQUESTS", "int", 8,
         "Default per-replica concurrent-request cap "
         "(reference max_ongoing_requests).",
         "serve", attr="serve_max_ongoing_requests"),
    Knob("RAY_TPU_SERVE_MAX_QUEUED_REQUESTS", "int", -1,
         "Default per-deployment queue cap beyond replica capacity "
         "(max_ongoing_requests x replicas): excess handle calls are shed "
         "with BackPressureError / HTTP 503 + Retry-After instead of "
         "queueing into latency collapse. -1 = unbounded (no shedding).",
         "serve", attr="serve_max_queued_requests"),
    Knob("RAY_TPU_SERVE_REQUEST_RETRIES", "int", 3,
         "Max times a handle call is re-sent to a DIFFERENT replica after a "
         "replica-death/unavailable failure (deployments with "
         "retryable=False never retry). User-code exceptions never retry.",
         "serve", attr="serve_request_retries"),
    Knob("RAY_TPU_SERVE_RETRY_BACKOFF_S", "float", 0.05,
         "Base of the jittered exponential backoff between serve request "
         "retries (attempt N sleeps ~base*2^(N-1), capped).",
         "serve", attr="serve_retry_backoff_s"),
    Knob("RAY_TPU_SERVE_RETRY_BACKOFF_MAX_S", "float", 2.0,
         "Cap on the serve request retry backoff.",
         "serve", attr="serve_retry_backoff_max_s"),
    Knob("RAY_TPU_SERVE_SUSPECT_TTL_S", "float", 30.0,
         "How long the handle router excludes a replica after a "
         "replica-death classified failure (the suspect list bridges the gap "
         "until the controller's health check removes it from the long-poll "
         "view).",
         "serve", attr="serve_suspect_ttl_s"),
    Knob("RAY_TPU_SERVE_DRAIN_TIMEOUT_S", "float", 30.0,
         "Default grace a DRAINING replica gets to finish in-flight requests "
         "on scale-down/rolling update/shutdown before it is killed anyway "
         "(per-deployment override: drain_timeout_s).",
         "serve", attr="serve_drain_timeout_s"),
    Knob("RAY_TPU_SERVE_AUTOSCALE_INTERVAL_S", "float", 0.0,
         "Tick period of the head-side serve autoscaling loop "
         "(serve/autoscaler.py). 0 (default) paces on the metrics-history "
         "scraper's frames (one decision pass per scrape), which keeps the "
         "loop and its inputs in lockstep.",
         "serve", attr="serve_autoscale_interval_s"),
    Knob("RAY_TPU_SERVE_AUTOSCALE_BURN_TICKS", "int", 2,
         "Consecutive ticks an SLO burn / queue-over-target signal must "
         "persist before the loop scales a deployment up (the short half of "
         "the hysteresis pair: one noisy scrape never resizes the fleet).",
         "serve", attr="serve_autoscale_burn_ticks"),
    Knob("RAY_TPU_SERVE_AUTOSCALE_CLEAN_TICKS", "int", 3,
         "Consecutive clean ticks (no burning SLO, no queue pressure) "
         "required before a scale-down is considered (the long half of the "
         "hysteresis pair; scale-down additionally needs the down-cooldown "
         "elapsed and no replica still DRAINING).",
         "serve", attr="serve_autoscale_clean_ticks"),
    Knob("RAY_TPU_SERVE_AUTOSCALE_UP_COOLDOWN_S", "float", 3.0,
         "Minimum seconds between successive scale-UPs of one deployment "
         "(lets the previous step's replicas absorb load before adding more).",
         "serve", attr="serve_autoscale_up_cooldown_s"),
    Knob("RAY_TPU_SERVE_AUTOSCALE_DOWN_COOLDOWN_S", "float", 30.0,
         "Minimum seconds after ANY scale change before a scale-down (a "
         "flapping SLO must not thrash the paged-KV pool with drain/start "
         "churn).",
         "serve", attr="serve_autoscale_down_cooldown_s"),
    Knob("RAY_TPU_SERVE_AUTOSCALE_QUEUE_TARGET", "float", 4.0,
         "Default desired in-flight requests per replica for mode=\"slo\" "
         "autoscaling (per-deployment override: "
         "AutoscalingConfig.target_queue_depth). The loop scales toward "
         "ceil(queue_depth / target).",
         "serve", attr="serve_autoscale_queue_target"),
    Knob("RAY_TPU_SERVE_AUTOSCALE_STARTUP_TIMEOUT_S", "float", 30.0,
         "How long a scale-up may sit below target before it is declared "
         "stuck: the deficit is handed to the node autoscaler as a demand "
         "hint, wedged STARTING replicas restart elsewhere, and the handle's "
         "anticipated-capacity admission window expires (shedding resumes).",
         "serve", attr="serve_autoscale_startup_timeout_s"),
    # -- llm
    Knob("RAY_TPU_PD_EXPORT_TTL_S", "float", 600.0,
         "Device-plane auto-release backstop for P/D prefill KV exports whose "
         "decode consumer crashed before acking.",
         "llm", attr="pd_export_ttl_s"),
    Knob("RAY_TPU_PD_EXPORT_MAX_LIVE", "int", 128,
         "Max un-acked P/D KV exports a prefill engine pins before LRU "
         "pruning (each pins device memory until the decode side pulls).",
         "llm", attr="pd_export_max_live"),
    Knob("RAY_TPU_PD_PAGED", "bool", True,
         "P/D KV handoff rides the paged streaming path: prefill publishes "
         "the KV region on the striped data plane and decode pulls it "
         "page-by-page over multiple streams, overlapped with decode bursts. "
         "Off = the original monolithic single-stream device-plane export.",
         "llm", attr="pd_paged"),
    Knob("RAY_TPU_PD_PAGE_BYTES", "int", 1 << 20,
         "Page size of the paged P/D KV handoff: the unit one puller stream "
         "fetches per ranged pull. Smaller pages spread better across "
         "streams; larger pages amortize per-pull framing.",
         "llm", attr="pd_page_bytes"),
    Knob("RAY_TPU_PD_PULL_STREAMS", "int", 4,
         "Concurrent puller streams a decode replica uses for one paged KV "
         "handoff (also the minimum stream count the prefill side's data "
         "server is provisioned for).",
         "llm", attr="pd_pull_streams"),
    Knob("RAY_TPU_PD_FETCH_TIMEOUT_S", "float", 60.0,
         "Overall deadline for one paged P/D KV fetch; past it the decode "
         "side fails the transfer with a typed DevicePlaneError and the "
         "router replays the request on the host path.",
         "llm", attr="pd_fetch_timeout_s"),
    Knob("RAY_TPU_PD_STAGING_BUFFERS", "int", 2,
         "Max recycled paged-handoff staging buffers a decode process pools. "
         "A fresh destination buffer costs a zero-fill page-fault pass per "
         "handoff; recycling skips it. Each pooled buffer holds one "
         "handoff's KV bytes of host memory; 0 disables pooling.",
         "llm", attr="pd_staging_buffers"),
    Knob("RAY_TPU_LLM_ENGINE_IDLE_WAIT_S", "float", 0.05,
         "Engine scheduler-loop sleep when no slot is active (admission "
         "latency floor for the first request of a burst).",
         "llm", attr="llm_engine_idle_wait_s"),
    Knob("RAY_TPU_LLM_MAX_NUM_SEQS", "int", 8,
         "Default decode-slot count for LLMConfig (continuous batching width).",
         "llm", attr="llm_max_num_seqs"),
    Knob("RAY_TPU_LLM_MAX_MODEL_LEN", "int", 1024,
         "Default per-slot KV capacity for LLMConfig.",
         "llm", attr="llm_max_model_len"),
    Knob("RAY_TPU_LLM_FUSED_STEPS", "int", 0,
         "Default fused decode burst width when LLMConfig.num_decode_steps is "
         "unset: the engine runs this many decode+sample steps on device per "
         "host sync. 0 = auto-tune from the measured host round trip vs the "
         "measured device step time.",
         "llm", attr="llm_fused_steps"),
    Knob("RAY_TPU_LLM_FUSED_STEPS_MAX", "int", 32,
         "Upper bound for the auto-tuned fused decode burst width (bounds "
         "both K-token streaming granularity and the log2(K) compiled decode "
         "program count).",
         "llm", attr="llm_fused_steps_max"),
    Knob("RAY_TPU_LLM_FUSED_SYNC_TARGET", "float", 0.15,
         "Auto-tune target for the host-sync share of a decode burst: K is "
         "raised until host_round_trip/(host_round_trip + K*device_step) "
         "drops to this fraction (subject to llm_fused_steps_max).",
         "llm", attr="llm_fused_sync_target"),
    Knob("RAY_TPU_LLM_PREFIX_MIN_HIT_TOKENS", "int", 0,
         "Prefix-cache pay-or-skip floor: a warm prefill only uses the cache "
         "when the cached-token count reaches this. 0 = auto — skip when the "
         "predicted compute saving (hit tokens x measured per-token prefill "
         "time) is below the measured dispatch round trip.",
         "llm", attr="llm_prefix_min_hit_tokens"),
    # -- train
    Knob("RAY_TPU_TRAIN_V2_ENABLED", "bool", False,
         "Route trainers through the v2 controller (FailurePolicy/"
         "ScalingPolicy; reference RAY_TRAIN_V2_ENABLED).",
         "train", attr="train_v2_enabled"),
    Knob("RAY_TPU_TRAIN_RESTART_BACKOFF_S", "float", 1.0,
         "Base of the bounded exponential backoff between Train worker-group "
         "restarts (failure N sleeps base*2^(N-1), capped). 0 disables.",
         "train", attr="train_restart_backoff_s"),
    Knob("RAY_TPU_TRAIN_RESTART_BACKOFF_MAX_S", "float", 30.0,
         "Cap on the Train restart backoff.",
         "train", attr="train_restart_backoff_max_s"),
    Knob("RAY_TPU_STORAGE_PATH", "str", None,
         "Default experiment storage path (default: ~/ray_tpu_results).",
         "train", attr="storage_path"),
    # -- ops
    Knob("RAY_TPU_MOE_GROUP_SIZE", "int", 4096,
         "Tokens per MoE dispatch group: dispatch/combine tensors are "
         "[group, experts, capacity], so memory is O(tokens x group).",
         "ops", attr="moe_group_size"),
    Knob("RAY_TPU_FLASH_BLOCK_Q", "int", 512,
         "Pallas flash-attention query-tile rows (MXU-aligned multiple of 8; "
         "512 saturates v5e at head_dim 64-128).",
         "ops", attr="flash_block_q"),
    Knob("RAY_TPU_FLASH_BLOCK_KV", "int", 512,
         "Pallas flash-attention key/value-tile rows.",
         "ops", attr="flash_block_kv"),
    Knob("RAY_TPU_CHUNKED_ATTENTION_MIN_LOGITS", "int", 1 << 20,
         "Sq*Skv above which non-pallas attention switches to the chunked "
         "online-softmax path (bounds the logits buffer on long context).",
         "ops", attr="chunked_attention_min_logits"),
    # -- observability
    Knob("RAY_TPU_METRICS_REPORT_INTERVAL_S", "float", 2.0,
         "Worker metric-snapshot push period to the head "
         "(reference metrics_report_interval_ms).",
         "observability", attr="metrics_report_interval_s"),
    Knob("RAY_TPU_TQDM_RENDER_INTERVAL_S", "float", 0.1,
         "Min seconds between driver-side tqdm_ray re-renders.",
         "observability", attr="tqdm_render_interval_s"),
    Knob("RAY_TPU_TRACING", "bool", False,
         "Enable OpenTelemetry-style span recording AND the hot-path "
         "telemetry event recorder (util/telemetry.py) at init.",
         "observability", attr="tracing"),
    Knob("RAY_TPU_TELEMETRY_RING_SIZE", "int", 8192,
         "Per-process telemetry ring-buffer capacity (events). Overflow drops "
         "the oldest events and logs a throttled warning at flush.",
         "observability", attr="telemetry_ring_size"),
    Knob("RAY_TPU_METRICS_SCRAPE_INTERVAL_S", "float", 5.0,
         "Head-side metrics-history scrape period: the merged cross-worker "
         "snapshot is sampled into a timestamped frame ring this often, "
         "feeding windowed rates/quantiles and the SLO engine. 0 disables "
         "the scraper.",
         "observability", attr="metrics_scrape_interval_s"),
    Knob("RAY_TPU_METRICS_HISTORY_SIZE", "int", 360,
         "Frames retained in the metrics-history ring (at the default 5 s "
         "scrape interval, 360 frames = 30 min of windowed history).",
         "observability", attr="metrics_history_size"),
    Knob("RAY_TPU_USAGE_STATS", "bool", False,
         "Record a local-only feature-usage summary in the session dir "
         "(never leaves the machine).",
         "observability", attr="usage_stats"),
    Knob("RAY_TPU_LP_DEBUG", "bool", False,
         "Verbose serve long-poll client logging.",
         "observability", attr="lp_debug"),
    Knob("RAY_TPU_DASHBOARD_PORT", "int", 8265,
         "Dashboard HTTP port (JSON API, /metrics exposition, web UI).",
         "observability", attr="dashboard_port"),
    Knob("RAY_TPU_CONTROL_NODE_AGG", "bool", True,
         "Node-agent metrics/telemetry pre-aggregation: each agent merges "
         "its local workers' pushes and ships ONE per-node delta per flush "
         "tick, making head-side scrape cost O(nodes) instead of "
         "O(workers). Off = agents relay every worker frame verbatim "
         "(the pre-PR-17 behavior; also the head's fallback for "
         "un-upgraded agents).",
         "observability", attr="control_node_agg"),
    Knob("RAY_TPU_CONTROL_NODE_FLUSH_S", "float", 2.0,
         "Node-agent aggregated-delta ship period (matches the worker "
         "metric report interval so history freshness is unchanged). The "
         "head's backpressure signal can widen the EFFECTIVE interval up "
         "to RAY_TPU_CONTROL_BACKPRESSURE_MAX_S.",
         "observability", attr="control_node_flush_s"),
    Knob("RAY_TPU_CONTROL_MAX_SERIES", "int", 1024,
         "Bounded-cardinality guard: max distinct label sets per metric "
         "(per-process registries AND the head-side merge). New label sets "
         "past the cap are dropped and counted in "
         "metrics_dropped_series_total — head memory stays bounded even "
         "when a tag value explodes (e.g. a request id mistakenly used as "
         "a label).",
         "observability", attr="control_max_series"),
    Knob("RAY_TPU_CONTROL_INLET_BOUND", "int", 256,
         "Control-RPC inlet backpressure bound: when more metrics/"
         "telemetry frames than this arrive at the head between two scrape "
         "ticks, the head raises its backpressure level and tells agents "
         "to widen their flush interval; below half the bound it steps "
         "back down. 0 disables backpressure.",
         "observability", attr="control_inlet_bound"),
    Knob("RAY_TPU_CONTROL_BACKPRESSURE_MAX_S", "float", 30.0,
         "Widest flush interval the head's backpressure signal may impose "
         "on node agents (the signal doubles the interval per level; "
         "level 0 clears back to the agent's own cadence).",
         "observability", attr="control_backpressure_max_s"),
    Knob("RAY_TPU_CONTROL_HISTORY_JOURNAL_FRAMES", "int", 24,
         "Metrics-history frames journaled through the GCS KV path after "
         "each scrape so SLO burn windows and the router's windowed-TTFT "
         "inputs survive a head restart (needs "
         "RAY_TPU_GCS_PERSISTENCE_PATH to persist across processes). "
         "0 disables the journal.",
         "observability", attr="control_history_journal_frames"),
    Knob("RAY_TPU_CONTROL_HISTORY_MAX_POINTS", "int", 120,
         "Max points per series in state.history_series()/ /api/history: "
         "longer windows are downsampled (stride-wise, newest kept) and "
         "the payload marked truncated, so `ray-tpu status --watch` never "
         "ships megabytes per refresh.",
         "observability", attr="control_history_max_points"),
    Knob("RAY_TPU_CONTROL_HISTORY_MAX_SERIES", "int", 64,
         "Max series entries in state.history_series()/ /api/history "
         "payloads before the rest are dropped and the payload marked "
         "truncated.",
         "observability", attr="control_history_max_series"),
    # -- autoscaler
    Knob("RAY_TPU_PROVISION_MAX_ATTEMPTS", "int", 4,
         "Inline create_node attempts for rate-limit/transient cloud errors "
         "before the failure escalates to the autoscaler backoff (reference "
         "gcp node.py retry loops).",
         "autoscaler", attr="provision_max_attempts"),
    Knob("RAY_TPU_PROVISION_BACKOFF_S", "float", 2.0,
         "Base for the jittered exponential inline-retry backoff in "
         "create_node.",
         "autoscaler", attr="provision_backoff_s"),
    Knob("RAY_TPU_LAUNCH_BACKOFF_MAX_S", "float", 600.0,
         "Cap on the autoscaler's per-node-type launch backoff after "
         "quota/stockout/permanent provision failures.",
         "autoscaler", attr="launch_backoff_max_s"),
    # -- chaos
    Knob("RAY_TPU_FAULT_INJECTION", "str", None,
         "Arm util/fault_injection.py fail points from the environment: "
         "'site=mode[@p=0.5][@n=3][@delay=0.1][@seed=7][;site2=...]' with "
         "mode error|delay|kill. Deterministic chaos for tests/drills; "
         "unset = every fail point is a no-op.",
         "chaos", attr="fault_injection"),
    Knob("RAY_TPU_HEAD_PID", "int", None,
         "Default target for ChaosController.kill_head() when no pid/Popen "
         "is passed: the standalone head process to SIGKILL in head-death "
         "chaos runs. Unset = kill_head requires an explicit target.",
         "chaos"),

    # -- core (worker plumbing + native build)
    Knob("RAY_TPU_NODE_IP", "str", None,
         "Operator override for the IP this node advertises to peers "
         "(device plane + data plane listeners); default: outbound-interface "
         "autodetection.",
         "core"),
    Knob("RAY_TPU_SANITIZE", "str", None,
         "Rebuild the native shm-store library under a sanitizer: "
         "address|thread|undefined (dev/debug; see _native/build.py).",
         "core"),
    Knob("RAY_TPU_WORKER_AUTHKEY", "str", None,
         "Hex authkey a spawned/containerized worker uses to dial back to "
         "its node (set by the worker pool at spawn).",
         "core", internal=True),
    Knob("RAY_TPU_WORKER_LOG_DIR", "str", None,
         "Directory a worker tees its stdout/stderr capture into (set by "
         "the node agent at spawn).",
         "core", internal=True),
    Knob("RAY_TPU_ARENA", "str", None,
         "Shared-memory arena name a worker attaches to (set per node; "
         "never shared across hosts).",
         "object-store", internal=True),
    # -- runtime-env (continued)
    Knob("RAY_TPU_DEFAULT_RUNTIME_ENV", "str", None,
         "JSON job-level default runtime env the head propagates to node "
         "agents (set by ray_tpu.init(runtime_env=...)).",
         "runtime-env", internal=True),
    # -- train (grad-sync worker knobs: GradSyncConfig.from_env/to_env)
    Knob("RAY_TPU_TRAIN_GRAD_SYNC_MODE", "str", "gspmd",
         "Gradient sync mode in the worker train step: gspmd/monolithic "
         "(implicit sync) or bucketed (overlapped per-bucket allreduce).",
         "train"),
    Knob("RAY_TPU_TRAIN_BUCKET_BYTES", "int", 4 * 1024 * 1024,
         "Max payload per gradient allreduce bucket (bucketed mode).",
         "train"),
    Knob("RAY_TPU_TRAIN_GRAD_SYNC_AXIS", "str", "dp",
         "Mesh axis the bucketed sync reduces over manually.",
         "train"),
    Knob("RAY_TPU_TRAIN_GRAD_COMPRESSION", "str", None,
         "int8 = on-device block-quantized gradient reduction.",
         "train"),
    Knob("RAY_TPU_TRAIN_GRAD_STOCHASTIC_ROUNDING", "bool", False,
         "Unbiased stochastic rounding in the int8 gradient quantizer.",
         "train"),
    Knob("RAY_TPU_TRAIN_QUANT_BLOCK_ELEMS", "int", 1024,
         "Elements per int8 scale block in the quantized reduction.",
         "train"),
    Knob("RAY_TPU_TRAIN_MIN_QUANT_ELEMS", "int", 256,
         "Gradient leaves smaller than this stay f32 under int8 compression.",
         "train"),
    Knob("RAY_TPU_TRAIN_SHARDED_UPDATE", "bool", False,
         "Cross-replica sharded (ZeRO-style) optimizer update.",
         "train"),
    Knob("RAY_TPU_TRAIN_UPDATE_AXES", "str", "dp,fsdp",
         "Mesh axes the sharded optimizer update shards state over.",
         "train"),
    # -- MPMD pipeline parallelism (train/mpmd_pipeline.py)
    Knob("RAY_TPU_PIPELINE_MICROBATCHES", "int", 4,
         "Microbatches per optimizer step in the MPMD pipeline runner "
         "(power of two keeps the 1/M cotangent exact in f32).",
         "train", attr="pipeline_microbatches"),
    Knob("RAY_TPU_PIPELINE_SCHEDULE", "str", "1f1b",
         "MPMD pipeline schedule: 1f1b (warmup/steady/cooldown, overlapped) "
         "or gpipe (all-forwards-then-all-backwards baseline).",
         "train", attr="pipeline_schedule"),
    Knob("RAY_TPU_PIPELINE_PREFETCH", "int", 2,
         "Microbatch blocks each stage pulls ahead of its schedule cursor "
         "(0 = unoverlapped transfers).",
         "train", attr="pipeline_prefetch"),
    Knob("RAY_TPU_PIPELINE_STREAMS", "int", 1,
         "Concurrent stripes per inter-stage block pull (ranged pull_into "
         "fan-out; blocks under 64 KiB always ride one stream).",
         "train", attr="pipeline_streams"),
    Knob("RAY_TPU_PIPELINE_TRANSPORT", "str", "auto",
         "Inter-stage activation transport: auto (device plane when this "
         "process has it, else host), host, or device.",
         "train", attr="pipeline_transport"),
    Knob("RAY_TPU_TRAIN_GRAD_SYNC_TELEMETRY", "bool", False,
         "Two-stage train step with per-bucket wait spans "
         "(train.step_phase telemetry).",
         "train"),
    Knob("RAY_TPU_TRAIN_JAX_INIT_TIMEOUT_S", "int", 60,
         "jax.distributed.initialize() deadline on a Train worker.",
         "train"),
    Knob("RAY_TPU_TRAIN_RANK", "str", None,
         "This Train worker's rank (set by the backend at worker setup).",
         "train", internal=True),
    Knob("RAY_TPU_TRAIN_WORLD_SIZE", "str", None,
         "Train worker-group world size (set by the backend).",
         "train", internal=True),
    Knob("RAY_TPU_TRAIN_COLLECTIVE_GROUP", "str", None,
         "Collective group name a Train worker joins for host-plane sync "
         "(set by the backend).",
         "train", internal=True),
    # -- rl (decoupled rollout/learn plane: rllib/rollout_plane.py)
    Knob("RAY_TPU_RL_QUEUE_DEPTH", "int", 8,
         "Bounded trajectory-block queue depth; when full the OLDEST "
         "announced block is evicted (freshest-data-wins).",
         "rl"),
    Knob("RAY_TPU_RL_MAX_BLOCK_LAG", "int", 4,
         "Max policy-version lag a block may have at take time; staler "
         "blocks are dropped (counted `expired`) instead of trained on.",
         "rl"),
    Knob("RAY_TPU_RL_CORRECTION", "str", "is_clip",
         "Off-policy correction for stale blocks: 'is_clip' (PPO ratio "
         "clipping over behaviour-policy GAE) or 'vtrace' (IMPALA-style "
         "current-policy V-trace targets).",
         "rl"),
    Knob("RAY_TPU_RL_WEIGHT_SYNC_INTERVAL", "int", 1,
         "Learner updates between weight broadcasts back over the "
         "zero-copy plane (workers adopt at block boundaries).",
         "rl"),
    Knob("RAY_TPU_RL_BLOCKS_PER_UPDATE", "int", 1,
         "Trajectory blocks consumed per learner update (rounded up to a "
         "multiple of num_learners).",
         "rl"),
    Knob("RAY_TPU_RL_TAKE_TIMEOUT_S", "float", 30.0,
         "How long one training step polls the block queue before "
         "returning empty-handed (learner-paced; never blocks workers).",
         "rl"),
    Knob("RAY_TPU_RL_PRODUCER_SLACK", "int", 2,
         "Queue depth beyond which rollout workers pace themselves instead "
         "of sampling blocks destined for eviction (<= 0: free-run).",
         "rl"),
    Knob("RAY_TPU_RL_HOST_SLICING", "bool", False,
         "Force the legacy host-side minibatch slicing path in "
         "Learner.update (one H2D copy per minibatch) — bench/debug only; "
         "default is device-resident gather.",
         "rl"),
    # -- storage / test hooks
    Knob("RAY_TPU_MOCK_FS_ROOT", "str", None,
         "Backing directory for the mock:// checkpoint filesystem "
         "(storage tests; default: a tempdir).",
         "train"),
    # -- bench gates (read by core_bench.py, not the runtime)
    Knob("RAY_TPU_TELEMETRY_OVERHEAD_PCT", "float", 3.0,
         "core_bench --telemetry-overhead gate: max hot-path overhead "
         "percent with telemetry on.",
         "bench"),
    Knob("RAY_TPU_CONTROL_P99_MS", "float", 250.0,
         "core_bench --control-plane gate: max p99 scrape->SLO->autoscaler "
         "decision latency (ms) at 1024 synthetic replicas.",
         "bench"),
    Knob("RAY_TPU_CONTROL_AGG_SPEEDUP", "float", 4.0,
         "core_bench --control-plane gate: min head-side cost ratio "
         "(per-worker scrape / node-delta scrape) at 256 synthetic "
         "replicas — node aggregation must be at least this much cheaper.",
         "bench"),
    Knob("RAY_TPU_SCRAPE_OVERHEAD_PCT", "float", 1.0,
         "core_bench --scrape-overhead gate: max pull-path interference "
         "percent from the metrics-history scraper.",
         "bench"),
    Knob("RAY_TPU_TEST_POOL", "str", None,
         "Marker env var the worker-per-env pool tests key pools on "
         "(no runtime meaning).",
         "bench", internal=True),
]


REGISTRY: Dict[str, Knob] = {k.env: k for k in KNOBS}
assert len(REGISTRY) == len(KNOBS), "duplicate knob env names"

SUBSYSTEMS: List[str] = []
for _k in KNOBS:
    if _k.subsystem not in SUBSYSTEMS:
        SUBSYSTEMS.append(_k.subsystem)


def get(env: str) -> Optional[Knob]:
    return REGISTRY.get(env)


def by_subsystem(subsystem: str) -> List[Knob]:
    return [k for k in KNOBS if k.subsystem == subsystem]


def _default_repr(k: Knob) -> str:
    if k.default is None:
        return "unset"
    if k.type == "bool":
        return "on" if k.default else "off"
    return str(k.default)


def render_table(subsystem: str) -> str:
    """One markdown knob table for a subsystem (internal entries are listed
    last and tagged; they are protocol, not operator flags)."""
    rows = sorted(by_subsystem(subsystem), key=lambda k: (k.internal, k.env))
    lines = ["| knob | type | default | description |",
             "|---|---|---|---|"]
    for k in rows:
        doc = k.doc.replace("|", "\\|")
        if k.internal:
            doc = "*(internal: set by the runtime, not an operator flag)* " + doc
        lines.append(f"| `{k.env}` | {k.type} | `{_default_repr(k)}` | {doc} |")
    return "\n".join(lines)


# README generation: everything between a `<!-- knobs:<subsystem> -->` /
# `<!-- /knobs -->` marker pair is owned by this registry. `ray-tpu lint`
# fails on drift; `ray-tpu lint --write-docs` rewrites the blocks in place.
_BEGIN = "<!-- knobs:{sub} (generated from ray_tpu/knobs.py — do not edit) -->"
_END = "<!-- /knobs -->"


def render_block(subsystem: str) -> str:
    return "\n".join([_BEGIN.format(sub=subsystem), render_table(subsystem), _END])


def generate_readme(text: str) -> str:
    """Rewrite every marked knob block in `text` from the live registry."""
    import re

    def _sub(m: "re.Match[str]") -> str:
        return render_block(m.group(1))

    pat = re.compile(
        r"<!-- knobs:([a-z-]+) \(generated from ray_tpu/knobs\.py[^>]*-->"
        r".*?<!-- /knobs -->",
        re.S)
    return pat.sub(_sub, text)
