"""TPU compute kernels: flash attention (Pallas), ring attention, fused ops.

The reference has no kernels of its own (attention lives in vLLM/torch — SURVEY.md §2.3);
here they are first-class because long-context and MFU targets depend on them.
"""
from .attention import attention  # noqa: F401
