"""Pallas TPU flash attention (forward + backward), causal + GQA + segment ids.

Blockwise online-softmax attention (flash v2 style): the S×S score matrix never
materializes in HBM; each (q-block, kv-block) tile is computed in VMEM and folded into
running (max, sum, acc) statistics. Causal q/kv tiles that are fully masked are skipped
entirely, so causal attention does half the FLOPs.

Layout inside the kernel is [B, H, S, D] ("BHSD") so the S×D tiles are contiguous; the
public wrapper takes BSHD like the rest of the framework. GQA is handled in the
BlockSpec index maps (kv head = q head // n_rep) — repeated KV heads are never
materialized.

Backward follows the standard two-kernel split: one pass computes dQ (grid over kv
blocks inner), one computes dK/dV (grid over q blocks inner), both recomputing the
block's probabilities from the saved logsumexp.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# tile sizes live in the flag registry: CONFIG.flash_block_q / flash_block_kv
NEG_INF = -1e30


def _block_sizes(sq: int, skv: int, bq: int, bkv: int):
    bq, bkv = min(bq, sq), min(bkv, skv)
    if sq % bq or skv % bkv:
        raise ValueError(f"seq lengths ({sq},{skv}) must be multiples of blocks ({bq},{bkv})")
    return bq, bkv


def _interpret() -> bool:
    """Pallas interpreter on non-TPU backends (CPU tests)."""
    return jax.default_backend() in ("cpu", "gpu")


# ------------------------------------------------------------------- forward kernel


def _fwd_kernel(
    q_ref,  # [bq, D]
    k_ref,  # [bkv, D]
    v_ref,  # [bkv, D]
    seg_q_ref,  # [bq, 128] or None
    seg_kv_ref,  # [bkv, 128] or None
    o_ref,  # [bq, D]
    lse_ref,  # [bq, 128] (lanes replicated)
    m_scr,  # VMEM [bq, 128] f32
    l_scr,  # VMEM [bq, 128] f32
    acc_scr,  # VMEM [bq, D] f32
    *,
    scale: float,
    causal: bool,
    bq: int,
    bkv: int,
):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[:]
        k = k_ref[:]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bkv]
        s = s * scale

        rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0) + qi * bq
        cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1) + kj * bkv
        if causal:
            s = jnp.where(cols <= rows, s, NEG_INF)
        if seg_q_ref is not None:
            seg_q = seg_q_ref[:, :1]  # [bq, 1]
            seg_kv = seg_kv_ref[:, :1]  # [bkv, 1]
            s = jnp.where(seg_q == seg_kv.T, s, NEG_INF)

        m_prev = m_scr[:, :1]  # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [bq, bkv]
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)

        acc = acc_scr[:] * alpha
        acc = acc + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:] = acc
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # Skip tiles strictly above the diagonal.
        @pl.when(kj * bkv <= qi * bq + (bq - 1))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[:] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = m_scr[:, :1] + jnp.log(l_safe)
        lse_ref[:] = jnp.broadcast_to(lse, lse_ref.shape).astype(lse_ref.dtype)


def _fwd(
    q: jax.Array,  # [B, H, Sq, D]
    k: jax.Array,  # [B, Hkv, Skv, D]
    v: jax.Array,
    seg_q: Optional[jax.Array],  # [B, Sq, 128] int32
    seg_kv: Optional[jax.Array],  # [B, Skv, 128]
    scale: float,
    causal: bool,
    bq: int,
    bkv: int,
):
    b, h, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    n_rep = h // hkv
    bq, bkv = _block_sizes(sq, skv, bq, bkv)
    grid = (b, h, pl.cdiv(sq, bq), pl.cdiv(skv, bkv))

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, kj: (bi, hi, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, bkv, d), lambda bi, hi, qi, kj: (bi, hi // n_rep, kj, 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    args = [q, k, v]
    if seg_q is not None:
        in_specs.append(pl.BlockSpec((1, bq, 128), lambda bi, hi, qi, kj: (bi, qi, 0)))
        in_specs.append(pl.BlockSpec((1, bkv, 128), lambda bi, hi, qi, kj: (bi, kj, 0)))
        args += [seg_q, seg_kv]

    def kernel(*refs):
        if seg_q is not None:
            q_ref, k_ref, v_ref, sq_ref, skv_ref, o_ref, lse_ref, m_s, l_s, a_s = refs
            sq_r, skv_r = sq_ref.at[0], skv_ref.at[0]
        else:
            q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, a_s = refs
            sq_r = skv_r = None
        _fwd_kernel(
            q_ref.at[0, 0],
            k_ref.at[0, 0],
            v_ref.at[0, 0],
            sq_r,
            skv_r,
            o_ref.at[0, 0],
            lse_ref.at[0, 0],
            m_s,
            l_s,
            a_s,
            scale=scale,
            causal=causal,
            bq=bq,
            bkv=bkv,
        )

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, kj: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 128), lambda bi, hi, qi, kj: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(*args)
    return out, lse[..., 0]  # lse: [B, H, Sq]


# ------------------------------------------------------------------ backward kernels


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seg_q_ref, seg_kv_ref, dq_ref, dq_scr,
    *, scale, causal, bq, bkv,
):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        q = q_ref[:]
        k = k_ref[:]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0) + qi * bq
        cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1) + kj * bkv
        mask = None
        if causal:
            mask = cols <= rows
        if seg_q_ref is not None:
            m2 = seg_q_ref[:, :1] == seg_kv_ref[:, :1].T
            mask = m2 if mask is None else (mask & m2)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse_ref[:, :1])  # [bq, bkv]
        do = do_ref[:].astype(jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[:].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[:, :1]) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        @pl.when(kj * bkv <= qi * bq + (bq - 1))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(kj == nk - 1)
    def _():
        dq_ref[:] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seg_q_ref, seg_kv_ref,
    dk_ref, dv_ref, dk_scr, dv_scr,
    *, scale, causal, bq, bkv,
):
    kj = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        q = q_ref[:]
        k = k_ref[:]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bkv]
        rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0) + qi * bq
        cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1) + kj * bkv
        mask = None
        if causal:
            mask = cols <= rows
        if seg_q_ref is not None:
            m2 = seg_q_ref[:, :1] == seg_kv_ref[:, :1].T
            mask = m2 if mask is None else (mask & m2)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse_ref[:, :1])  # [bq, bkv]
        do = do_ref[:].astype(jnp.float32)
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v_ref[:].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[:, :1]) * scale  # [bq, bkv]
        dk_scr[:] += jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        @pl.when(qi * bq + (bq - 1) >= kj * bkv)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(
    q, k, v, seg_q, seg_kv, out, lse, dout, scale, causal, bq, bkv
):
    b, h, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    n_rep = h // hkv
    bq_, bkv_ = _block_sizes(sq, skv, bq, bkv)

    # delta_i = sum_d(dO * O): rowwise, cheap in XLA.
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)  # [B,H,Sq]
    lse_l = jnp.broadcast_to(lse[..., None], (*lse.shape, 128)).astype(jnp.float32)
    delta_l = jnp.broadcast_to(delta[..., None], (*delta.shape, 128))

    # --- dQ pass: grid (b, h, nq, nk) ---
    q_spec = pl.BlockSpec((1, 1, bq_, d), lambda bi, hi, qi, kj: (bi, hi, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, bkv_, d), lambda bi, hi, qi, kj: (bi, hi // n_rep, kj, 0))
    row_spec = pl.BlockSpec((1, 1, bq_, 128), lambda bi, hi, qi, kj: (bi, hi, qi, 0))
    in_specs = [q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec]
    args = [q, k, v, dout, lse_l, delta_l]
    has_seg = seg_q is not None
    if has_seg:
        in_specs.append(pl.BlockSpec((1, bq_, 128), lambda bi, hi, qi, kj: (bi, qi, 0)))
        in_specs.append(pl.BlockSpec((1, bkv_, 128), lambda bi, hi, qi, kj: (bi, kj, 0)))
        args += [seg_q, seg_kv]

    def dq_kernel(*refs):
        if has_seg:
            (qr, kr, vr, dor, lser, deltar, sqr, skvr, dqr, dqs) = refs
            sq_r, skv_r = sqr.at[0], skvr.at[0]
        else:
            (qr, kr, vr, dor, lser, deltar, dqr, dqs) = refs
            sq_r = skv_r = None
        _bwd_dq_kernel(
            qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], dor.at[0, 0], lser.at[0, 0],
            deltar.at[0, 0], sq_r, skv_r, dqr.at[0, 0], dqs,
            scale=scale, causal=causal, bq=bq_, bkv=bkv_,
        )

    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, pl.cdiv(sq, bq_), pl.cdiv(skv, bkv_)),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, bq_, d), lambda bi, hi, qi, kj: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq_, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(*args)

    # --- dK/dV pass: grid (b, h, nk, nq); kv head accumulates over its rep group ---
    # For GQA we accumulate per q-head then sum over the rep group in XLA.
    q_spec2 = pl.BlockSpec((1, 1, bq_, d), lambda bi, hi, kj, qi: (bi, hi, qi, 0))
    kv_spec2 = pl.BlockSpec((1, 1, bkv_, d), lambda bi, hi, kj, qi: (bi, hi // n_rep, kj, 0))
    row_spec2 = pl.BlockSpec((1, 1, bq_, 128), lambda bi, hi, kj, qi: (bi, hi, qi, 0))
    in_specs2 = [q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2]
    args2 = [q, k, v, dout, lse_l, delta_l]
    if has_seg:
        in_specs2.append(pl.BlockSpec((1, bq_, 128), lambda bi, hi, kj, qi: (bi, qi, 0)))
        in_specs2.append(pl.BlockSpec((1, bkv_, 128), lambda bi, hi, kj, qi: (bi, kj, 0)))
        args2 += [seg_q, seg_kv]

    def dkv_kernel(*refs):
        if has_seg:
            (qr, kr, vr, dor, lser, deltar, sqr, skvr, dkr, dvr, dks, dvs) = refs
            sq_r, skv_r = sqr.at[0], skvr.at[0]
        else:
            (qr, kr, vr, dor, lser, deltar, dkr, dvr, dks, dvs) = refs
            sq_r = skv_r = None
        _bwd_dkv_kernel(
            qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], dor.at[0, 0], lser.at[0, 0],
            deltar.at[0, 0], sq_r, skv_r, dkr.at[0, 0], dvr.at[0, 0], dks, dvs,
            scale=scale, causal=causal, bq=bq_, bkv=bkv_,
        )

    dk_per_h, dv_per_h = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, pl.cdiv(skv, bkv_), pl.cdiv(sq, bq_)),
        in_specs=in_specs2,
        out_specs=[
            pl.BlockSpec((1, 1, bkv_, d), lambda bi, hi, kj, qi: (bi, hi, kj, 0)),
            pl.BlockSpec((1, 1, bkv_, d), lambda bi, hi, kj, qi: (bi, hi, kj, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, skv, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, skv, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bkv_, d), jnp.float32),
            pltpu.VMEM((bkv_, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(*args2)

    if n_rep > 1:
        dk = dk_per_h.reshape(b, hkv, n_rep, skv, d).sum(axis=2)
        dv = dv_per_h.reshape(b, hkv, n_rep, skv, d).sum(axis=2)
    else:
        dk, dv = dk_per_h, dv_per_h
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ----------------------------------------------------------------------- public API


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_bhsd(q, k, v, seg_lanes, scale, causal, bq, bkv):
    seg_q, seg_kv = (seg_lanes if seg_lanes is not None else (None, None))
    out, _ = _fwd(q, k, v, seg_q, seg_kv, scale, causal, bq, bkv)
    return out


def _flash_fwd_rule(q, k, v, seg_lanes, scale, causal, bq, bkv):
    seg_q, seg_kv = (seg_lanes if seg_lanes is not None else (None, None))
    out, lse = _fwd(q, k, v, seg_q, seg_kv, scale, causal, bq, bkv)
    return out, (q, k, v, seg_lanes, out, lse)


def _flash_bwd_rule(scale, causal, bq, bkv, res, dout):
    q, k, v, seg_lanes, out, lse = res
    seg_q, seg_kv = (seg_lanes if seg_lanes is not None else (None, None))
    dq, dk, dv = _bwd(q, k, v, seg_q, seg_kv, out, lse, dout, scale, causal, bq, bkv)
    return dq, dk, dv, None


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Skv, Hkv, D]
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,  # [B, Skv]
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
) -> jax.Array:
    """BSHD flash attention. Sq must equal Skv when segment_ids are used."""
    if block_q is None or block_kv is None:
        from ray_tpu.config import CONFIG

        block_q = block_q if block_q is not None else CONFIG.flash_block_q
        block_kv = block_kv if block_kv is not None else CONFIG.flash_block_kv
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d**0.5)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    seg_lanes = None
    if segment_ids is not None:
        sq = q.shape[1]
        seg_q = jnp.broadcast_to(
            segment_ids[:, -sq:, None].astype(jnp.int32), (q.shape[0], sq, 128)
        )
        seg_kv = jnp.broadcast_to(
            segment_ids[:, :, None].astype(jnp.int32), (*segment_ids.shape, 128)
        )
        seg_lanes = (seg_q, seg_kv)
    out = _flash_bhsd(qt, kt, vt, seg_lanes, scale, causal, block_q, block_kv)
    return out.transpose(0, 2, 1, 3)
