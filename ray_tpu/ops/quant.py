"""Weight-only int8 quantization for serving (W8A16).

Capability parity: the reference serving stack inherits vLLM quantization via
engine_kwargs pass-through (python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_models.py); a TPU-native engine provides it directly. Decode is HBM-
bandwidth-bound: storing weights as int8 + per-output-channel scales halves the
bytes each decode step streams from HBM. XLA fuses the int8->bf16 convert and
the scale multiply into the dot's operand read, so the MXU still computes in
bf16 — no accuracy cliff from int8 accumulation, ~2x weight-read bandwidth.

Per-output-channel symmetric quantization: for a weight contracted over its
FIRST axis (all llama projections are stored [d_in, ...out]), scales are
max|w| / 127 over d_in, one per output unit — the rank-preserving layout that
stacks cleanly under lax.scan'd layers.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp


class QTensor(NamedTuple):
    """int8 weight + per-output-channel scales. A pytree: stacks under scan,
    shards per-leaf (q like the fp weight, s replicated/matching out axes)."""

    q: jax.Array  # int8, same shape as the original weight
    s: jax.Array  # f32, original shape with the contraction axis kept as 1
    # (broadcast-ready, so dequant needs no axis bookkeeping — the same QTensor
    # works for dense [D,F] weights and expert-stacked [E,D,F] weights)

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self):
        return self.q.nbytes + self.s.nbytes


def quantize(w: jax.Array, contract_axis: int = 0) -> QTensor:
    """Symmetric per-output-channel int8 quantization over contract_axis."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=contract_axis, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-12)
    q = jnp.round(w.astype(jnp.float32) / scale)
    return QTensor(q=jnp.clip(q, -127, 127).astype(jnp.int8),
                   s=scale.astype(jnp.float32))


def dequant(t: QTensor, dtype) -> jax.Array:
    """Rehydrate to `dtype`; inside jit XLA fuses convert+scale into the
    consuming dot's operand read (the int8 bytes are what HBM streams)."""
    return t.q.astype(dtype) * t.s.astype(dtype)


def as_weight(p: Any, dtype) -> jax.Array:
    """THE accessor model code uses: dequants a QTensor, casts a plain array."""
    if isinstance(p, QTensor):
        return dequant(p, dtype)
    return p.astype(dtype)


# -- device-side blockwise int8 (gradient-sync reduction format) -----------------------
# The jnp analogue of quantize_np below: same symmetric block-scale scheme
# (scale = max|x|/127 per block of the flat element order, clip to [-127,127])
# but traced into the train step, where the compressed all-reduce of
# train/grad_sync.py quantizes each rank's gradient contribution before the
# device collective (EQuARX-style in-XLA compression, arxiv 2506.17615).

def quantize_blockwise(x: jax.Array, block_elems: int = 1024,
                       key: Optional[jax.Array] = None):
    """Blockwise symmetric int8 of any-shape `x` (flattened): returns
    (q int8 [nblocks, block_elems], scales f32 [nblocks, 1]); the tail block is
    zero-padded. `key` switches round-nearest to stochastic rounding
    (floor(x/scale + u), u~U[0,1)) — unbiased, so quantization error averages
    out across steps instead of accumulating as bias."""
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.size
    nblocks = max(1, -(-n // block_elems))
    pad = nblocks * block_elems - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    blocks = flat.reshape(nblocks, block_elems)
    amax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-12)
    scaled = blocks / scale
    if key is not None:
        q = jnp.floor(scaled + jax.random.uniform(key, blocks.shape))
    else:
        q = jnp.round(scaled)
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale.astype(jnp.float32)


def dequant_blockwise(q: jax.Array, scales: jax.Array, n: int, dtype) -> jax.Array:
    """Inverse of quantize_blockwise: flat [n] array of `dtype`."""
    out = q.astype(jnp.float32) * scales
    return out.reshape(-1)[:n].astype(dtype)


# -- host-side blockwise int8 (collective wire format) ---------------------------------
# Same symmetric scheme as quantize() above (scale = max|x|/127, clip to
# [-127, 127]) but numpy-native and blocked along the flat element order: the
# host-plane collective ring compresses transfer chunks on CPU, where a jax
# dispatch per chunk would dominate the quantization itself (EQuARX-style
# compressed all-reduce, arxiv 2506.17615).

def quantize_np(x: "np.ndarray", block_elems: int = 4096):
    """Blockwise symmetric int8: returns (q int8 [n], scales f32 [ceil(n/block)])."""
    import numpy as np

    flat = np.ascontiguousarray(x).reshape(-1).astype(np.float32, copy=False)
    n = flat.size
    if n == 0:
        return np.empty(0, np.int8), np.empty(0, np.float32)
    nblocks = -(-n // block_elems)
    pad = nblocks * block_elems - n
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    blocks = flat.reshape(nblocks, block_elems)
    amax = np.abs(blocks).max(axis=1)
    scales = np.maximum(amax / 127.0, 1e-12).astype(np.float32)
    q = np.clip(np.round(blocks / scales[:, None]), -127, 127).astype(np.int8)
    return q.reshape(-1)[:n], scales


def dequant_np(q: "np.ndarray", scales: "np.ndarray", block_elems: int, dtype):
    """Inverse of quantize_np; returns a 1-D array of `dtype` with q.size elements."""
    import numpy as np

    n = q.size
    if n == 0:
        return np.empty(0, dtype)
    nblocks = scales.size
    pad = nblocks * block_elems - n
    full = np.concatenate([q, np.zeros(pad, np.int8)]) if pad else q
    out = full.reshape(nblocks, block_elems).astype(np.float32) * scales[:, None]
    return out.reshape(-1)[:n].astype(dtype)


# Llama layer weights eligible for weight-only quantization. All are stored
# with d_in first (embed lookup table and norms excluded: gathers and
# elementwise ops do not stream per-token weight bytes the way matmuls do).
# In MoE layers the same keys hold EXPERT-STACKED weights [E, d_in, out] whose
# contraction axis is 1 — distinguished by rank below. The router [D, E] stays
# fp: it is tiny and routing decisions are the accuracy-critical bits.
LLAMA_QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_llama_params(params: dict) -> dict:
    """Quantize a llama param tree's layer matmuls in place-shape (scan-stacked
    layers quantize per layer via vmap so scales stay per-layer)."""
    out = dict(params)
    layers = params["layers"]

    def _maybe_quant(name, p, layer_keys):
        if name not in LLAMA_QUANT_KEYS:
            return p
        # Expert-stacked weights exist only in MoE layers (marked by their
        # "router") and only for the MLP keys — the attention projections are
        # rank-3 too ([d_in, heads, head_dim]), so rank alone cannot decide.
        expert = "router" in layer_keys and name in ("w_gate", "w_up", "w_down")
        axis = 1 if expert else 0  # experts: [E, d_in, out] contracts d_in
        if isinstance(layers, dict):  # scanned: leading layer axis
            return jax.vmap(lambda w: quantize(w, axis))(p)
        return quantize(p, axis)

    if isinstance(layers, dict):
        out["layers"] = {k: _maybe_quant(k, v, layers.keys())
                         for k, v in layers.items()}
    else:
        out["layers"] = [{k: _maybe_quant(k, v, lyr.keys())
                          for k, v in lyr.items()}
                         for lyr in layers]
    if "lm_head" in params:
        # the untied head [d_model, vocab] is often the single largest weight
        # a decode step streams; every head consumer goes through as_weight
        out["lm_head"] = quantize(params["lm_head"], 0)
    return out
