"""Ring attention: sequence-parallel attention over the `sp` mesh axis.

Long-context capability the reference lacks natively (SURVEY.md §2.3 "Sequence/context
parallelism" row and §5: Ray delegates long context to vLLM/DeepSpeed; here it is
first-class). Two schemes:

- `ring_attention`: blockwise attention with online-softmax accumulation while K/V chunks
  rotate around the ICI ring via `lax.ppermute` (Ring Attention, Liu et al.). Memory per
  chip is O(S_local²) for the running tile, activations stay sequence-sharded end-to-end.
- `ulysses_attention`: all-to-all reshard (seq-sharded → head-sharded), full-sequence
  attention locally, reshard back (DeepSpeed-Ulysses). Cheaper at short rings when
  n_heads % sp == 0; two all-to-alls instead of sp ppermutes.

Both are *collective* ops: they must run inside `shard_map` (or any SPMD region) where
`axis_name` is bound. `*_sharded` wrappers apply the shard_map with the framework's
standard activation layout P((dp,fsdp), sp, tp, None) over BSHD tensors.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _repeat_kv_heads(k: jax.Array, n_rep: int) -> jax.Array:
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def _chunk_accumulate(q, k, v, scale, q_pos, kv_pos, causal, m, l, acc, seg_q=None, seg_kv=None):
    """Fold one KV chunk into running online-softmax stats.

    q: [B,Sq,H,D]; k/v: [B,Skv,H,D]; q_pos/kv_pos: global positions [Sq]/[Skv];
    m,l: [B,H,Sq] f32; acc: [B,H,Sq,D] f32.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    mask = jnp.ones((q_pos.shape[0], kv_pos.shape[0]), bool)
    if causal:
        mask = kv_pos[None, :] <= q_pos[:, None]
    if seg_q is not None:
        mask = mask[None, :, :] & (seg_q[:, :, None] == seg_kv[:, None, :])
        mask = mask[:, None, :, :]  # [B,1,Sq,Skv]
    else:
        mask = mask[None, None, :, :]
    s = jnp.where(mask, s, NEG_INF)
    m_chunk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_chunk)
    # p is explicitly zeroed where masked: exp(s - m_new) is garbage when a whole row is
    # masked in this chunk (s == m_new == NEG_INF → exp(0) = 1).
    p = jnp.where(mask, jnp.exp(s - m_new[..., None]), 0.0)
    alpha = jnp.exp(m - m_new)
    l_new = alpha * l + jnp.sum(p, axis=-1)
    acc_new = alpha[..., None] * acc + jnp.einsum(
        "bhqk,bkhd->bhqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    return m_new, l_new, acc_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Collective ring attention. Call inside shard_map with seq sharded over axis_name.

    q/k/v: LOCAL chunks [B, S_local, H|Hkv, D] (BSHD); the global sequence is the
    concatenation over the ring in axis-index order. Returns local out [B, S_local, H, D].
    """
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv_heads(k, n_rep)
    v = _repeat_kv_heads(v, n_rep)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d**0.5)
    sp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, s_loc, h, _ = q.shape
    q_pos = idx * s_loc + jnp.arange(s_loc)

    # The scan carry must carry q's full varying-axes set (sp, plus any outer manual
    # axes like pp when nested inside a pipeline stage) or scan rejects the carry types.
    from ray_tpu.parallel.sharding import vary_like

    def _vary(z):
        return vary_like(z, q, extra=(axis_name,))
    m0 = _vary(jnp.full((b, h, s_loc), NEG_INF, jnp.float32))
    l0 = _vary(jnp.zeros((b, h, s_loc), jnp.float32))
    acc0 = _vary(jnp.zeros((b, h, s_loc, d), jnp.float32))
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def body(carry, step):
        k_cur, v_cur, seg_cur, m, l, acc = carry
        src = (idx - step) % sp  # ring shift moved chunk `src` onto this device at `step`
        kv_pos = src * s_loc + jnp.arange(s_loc)
        m, l, acc = _chunk_accumulate(
            q, k_cur, v_cur, scale, q_pos, kv_pos, causal, m, l, acc,
            seg_q=segment_ids, seg_kv=seg_cur,
        )
        # Rotate AFTER consuming; on the last step the rotation restores original owners
        # (and XLA dead-code-eliminates it if unused).
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        seg_nxt = (
            lax.ppermute(seg_cur, axis_name, perm) if seg_cur is not None else None
        )
        return (k_nxt, v_nxt, seg_nxt, m, l, acc), None

    (_, _, _, m, l, acc), _ = lax.scan(
        body, (k, v, segment_ids, m0, l0, acc0), jnp.arange(sp)
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
    attn_fn=None,
) -> jax.Array:
    """Collective Ulysses attention: all-to-all seq↔heads reshard around full attention.

    Requires n_heads (and n_kv_heads) divisible by the axis size. attn_fn defaults to the
    framework's dispatching `ops.attention` so the local full-seq attention still hits the
    Pallas kernel on TPU.
    """
    from .attention import attention as default_attn

    attn_fn = attn_fn or default_attn
    sp = lax.psum(1, axis_name)

    def to_seq(x):  # [B, S/sp, H, D] -> [B, S, H/sp, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def to_heads(x):  # [B, S, H/sp, D] -> [B, S/sp, H, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    if q.shape[2] % sp or k.shape[2] % sp:
        raise ValueError(
            f"ulysses needs heads divisible by sp axis: q heads {q.shape[2]}, "
            f"kv heads {k.shape[2]}, sp {sp}"
        )
    out = attn_fn(to_seq(q), to_seq(k), to_seq(v), causal=causal, scale=scale)
    return to_heads(out)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh=None,
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
    impl: str = "ring",
    axis_name: str = "sp",
) -> jax.Array:
    """shard_map wrapper over global BSHD tensors, manual over the `sp` axis ONLY.

    Batch/head dims stay in GSPMD auto mode (dp/fsdp/tp — and pp when nested inside a
    pipeline stage), so this composes with every other parallelism axis. Usable inside a
    jitted train step traced under `use_mesh(mesh)` (mesh=None → ambient mesh).
    """
    fn = {"ring": ring_attention, "ulysses": ulysses_attention}[impl]
    spec = P(None, axis_name, None, None)
    in_specs = (spec, spec, spec)
    args = (q, k, v)
    kwargs = dict(axis_name=axis_name, causal=causal, scale=scale)
    if segment_ids is not None:
        if impl != "ring":
            raise NotImplementedError("segment_ids only supported with impl='ring'")
        in_specs = in_specs + (P(None, axis_name),)
        args = args + (segment_ids,)

        def wrapped(q, k, v, seg):
            return ring_attention(q, k, v, segment_ids=seg, **kwargs)

    else:

        def wrapped(q, k, v):
            return fn(q, k, v, **kwargs)

    mapped = jax.shard_map(
        wrapped,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=spec,
        axis_names={axis_name},
    )
    return mapped(*args)
