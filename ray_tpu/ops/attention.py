"""Attention entry point with backend dispatch.

`attention()` is the single call sites use; it routes to the Pallas TPU flash kernel
when running on TPU and to a pure-XLA reference implementation elsewhere (CPU tests,
debugging). Both accept GQA (n_kv_heads <= n_heads) and causal masking.

Shapes (batch, seq, heads, head_dim) throughout — "BSHD".
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, Hkv, D] -> [B, S, Hkv*n_rep, D] by head repetition (GQA)."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    q_offset: Optional[jax.Array] = None,
    kv_valid_len: Optional[jax.Array] = None,
) -> jax.Array:
    """Pure-XLA attention. Numerically the ground truth for the Pallas kernel tests.

    q: [B, Sq, H, D]; k/v: [B, Skv, Hkv, D]. Returns [B, Sq, H, D].
    `segment_ids`: [B, Skv] int array; attention only within equal segments (packing).
    `q_offset`: kv index of query row 0 (decode-with-cache); default aligns the ends.
    `kv_valid_len`: kv slots >= this are masked out (padded cache tail).
    """
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d**0.5)
    # f32 logits regardless of input dtype: MXU accumulates in f32 on TPU anyway.
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    sq, skv = q.shape[1], k.shape[1]
    kj = jnp.arange(skv)[None, :]
    if causal:
        if q_offset is None:
            q_offset = skv - sq
        qi = jnp.arange(sq)[:, None] + q_offset
        logits = jnp.where(kj <= qi, logits, -jnp.inf)
    if kv_valid_len is not None:
        logits = jnp.where(kj < kv_valid_len, logits, -jnp.inf)
    if segment_ids is not None:
        seg_q = segment_ids[:, -sq:]
        mask = seg_q[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(mask[:, None, :, :], logits, -jnp.inf)
    # Rows with no valid kv (fully masked) softmax to NaN; zero them instead.
    probs = jnp.nan_to_num(jax.nn.softmax(logits, axis=-1))
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


def attention_chunked(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    q_offset: Optional[jax.Array] = None,
    kv_valid_len: Optional[jax.Array] = None,
    block_kv: int = 512,
) -> jax.Array:
    """Online-softmax attention over KV blocks ("flash in XLA").

    Scans KV in `block_kv` chunks with a running (max, sum, acc) carry, so peak
    memory is O(B*H*Sq*block_kv) instead of O(B*H*Sq*Skv). Pure lax.scan — compiles
    on any backend; the fallback for long sequences when the Pallas kernel can't
    tile the shape (and the path the 8B HBM-budget proof compiles on CPU).
    Same masking surface as attention_reference.
    """
    n_rep = q.shape[2] // k.shape[2]
    b, sq, h, d = q.shape
    skv = k.shape[1]
    scale = scale if scale is not None else 1.0 / (d**0.5)
    n_blk = -(-skv // block_kv)
    pad = n_blk * block_kv - skv
    seg_q = None if segment_ids is None else segment_ids[:, -sq:]
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        if segment_ids is not None:
            # Padded slots get segment id -1 (never matches a real segment).
            segment_ids = jnp.pad(segment_ids, ((0, 0), (0, pad)), constant_values=-1)
    if q_offset is None:
        q_offset = skv - sq
    qi = jnp.arange(sq)[:, None] + q_offset  # [Sq, 1] absolute kv positions

    # Chunk the UN-repeated kv heads; GQA repetition happens per 512-slot block
    # inside the scan body so the repeated copies never exist over the full Skv.
    hkv = k.shape[2]
    kb = k.reshape(b, n_blk, block_kv, hkv, d).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(b, n_blk, block_kv, hkv, d).transpose(1, 0, 2, 3, 4)
    seg_b = (
        None
        if segment_ids is None
        else segment_ids.reshape(b, n_blk, block_kv).transpose(1, 0, 2)
    )
    blk_idx = jnp.arange(n_blk)

    def body(carry, xs):
        m, l, acc = carry
        if seg_b is None:
            i, kc, vc = xs
            seg_c = None
        else:
            i, kc, vc, seg_c = xs
        kc = _repeat_kv(kc, n_rep)
        vc = _repeat_kv(vc, n_rep)
        kj = i * block_kv + jnp.arange(block_kv)[None, :]  # [1, blk]
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kc, preferred_element_type=jnp.float32)
        logits = logits * scale
        neg = jnp.float32(-1e30)  # finite: keeps fully-masked rows NaN-free
        if causal:
            logits = jnp.where((kj <= qi)[None, None], logits, neg)
        valid = kv_valid_len if kv_valid_len is not None else skv
        logits = jnp.where((kj < valid)[None, None], logits, neg)
        if seg_c is not None:
            mask = seg_q[:, :, None] == seg_c[:, None, :]  # [B, Sq, blk]
            logits = jnp.where(mask[:, None], logits, neg)
        blk_max = jnp.max(logits, axis=-1)  # [B, H, Sq]
        new_m = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(logits - new_m[..., None])  # [B, H, Sq, blk]
        # Kill masked slots exactly: when a whole row is masked new_m == neg and
        # exp(logits - new_m) == 1, which would silently average v.
        p = jnp.where(logits > neg * 0.5, p, 0.0)
        l = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vc.dtype), vc)
        acc = acc * corr.transpose(0, 2, 1)[..., None] + pv.astype(jnp.float32)
        return (new_m, l, acc), None

    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    acc0 = jnp.zeros((b, sq, h, d), jnp.float32)
    xs = (blk_idx, kb, vb) if seg_b is None else (blk_idx, kb, vb, seg_b)
    (m, l, acc), _ = jax.lax.scan(body, (m0, l0, acc0), xs)
    l_t = l.transpose(0, 2, 1)[..., None]  # [B, Sq, H, 1]
    out = jnp.where(l_t > 0, acc / jnp.maximum(l_t, 1e-30), 0.0)
    return out.astype(q.dtype)


# Below this many Sq*Skv logit elements the full [B,H,Sq,Skv] tensor is small enough
# that the one-shot reference path fuses better than a scan of blocks. A product
# threshold keeps single-row decode (Sq=1, any cache length) on the fused path —
# its logits are [B,H,1,Skv], tiny, and a sequential block scan would only add
# per-token latency.
def _chunked_min_logits() -> int:
    """CONFIG.chunked_attention_min_logits, read at trace time."""
    from ray_tpu.config import CONFIG

    return CONFIG.chunked_attention_min_logits

_logged_fallbacks: set = set()


def _log_fallback_once(q_shape, k_shape, impl: str) -> None:
    """On-TPU shapes that miss the Pallas kernel get a one-time warning — the
    perf cliff (Mosaic can't tile e.g. head_dim 64) should be visible, not silent."""
    key = (tuple(q_shape), tuple(k_shape))
    if key in _logged_fallbacks:
        return
    _logged_fallbacks.add(key)
    import logging

    logging.getLogger(__name__).warning(
        "attention: TPU shape q=%s kv=%s is not Mosaic-tileable "
        "(head_dim %% 128 or seq block alignment); using %s XLA path",
        tuple(q_shape), tuple(k_shape), impl,
    )


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    q_offset: Optional[jax.Array] = None,
    kv_valid_len: Optional[jax.Array] = None,
    impl: str = "auto",
) -> jax.Array:
    """Dispatching attention. impl: auto|pallas|chunked|reference.

    The Pallas path currently covers the training shape (no cache offsets, optional
    segment ids); decode-with-cache shapes use the XLA path, which fuses well anyway.
    """
    if impl == "auto":
        on_tpu = jax.default_backend() not in ("cpu", "gpu")
        # The pallas kernel's causal mask assumes query row i is absolute position i,
        # i.e. Sq == Skv; any offset/partial-window shape takes the XLA path.
        same_len = q.shape[1] == k.shape[1]
        # Mosaic tiles the lane (last) dim at 128 and sublanes at 8, and the
        # kernel requires seqs to be block-multiples once they exceed one
        # block: geometries the kernel can't tile (head_dim 16, seq 16, kv 20,
        # seq 520...) must fall back to XLA or TPU compile fails
        # ("slice shape must be aligned to tiling")
        def seq_ok(n: int, block: int) -> bool:
            return n % 8 == 0 and (n <= block or n % block == 0)

        from ray_tpu.config import CONFIG

        tileable = (q.shape[-1] % 128 == 0
                    and seq_ok(q.shape[1], CONFIG.flash_block_q)
                    and seq_ok(k.shape[1], CONFIG.flash_block_kv))
        if (on_tpu and tileable and q_offset is None and kv_valid_len is None
                and (same_len or not causal)):
            impl = "pallas"
        elif q.shape[1] * k.shape[1] >= _chunked_min_logits():
            # Long sequences that can't take the Pallas kernel: blockwise online
            # softmax keeps peak memory O(Sq*block) instead of O(Sq*Skv).
            impl = "chunked"
        else:
            impl = "reference"
        # Warn only for shapes that WOULD have hit Pallas but for tiling — decode
        # shapes (offsets/valid-len, Sq != Skv) are deliberately XLA-routed.
        if (impl != "pallas" and on_tpu and not tileable
                and q_offset is None and kv_valid_len is None
                and (same_len or not causal)):
            _log_fallback_once(q.shape, k.shape, impl)
    if impl == "pallas":
        from .flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, segment_ids=segment_ids, scale=scale)
    if impl == "chunked":
        return attention_chunked(
            q,
            k,
            v,
            causal=causal,
            segment_ids=segment_ids,
            scale=scale,
            q_offset=q_offset,
            kv_valid_len=kv_valid_len,
        )
    return attention_reference(
        q,
        k,
        v,
        causal=causal,
        segment_ids=segment_ids,
        scale=scale,
        q_offset=q_offset,
        kv_valid_len=kv_valid_len,
    )
