"""Attention entry point with backend dispatch.

`attention()` is the single call sites use; it routes to the Pallas TPU flash kernel
when running on TPU and to a pure-XLA reference implementation elsewhere (CPU tests,
debugging). Both accept GQA (n_kv_heads <= n_heads) and causal masking.

Shapes (batch, seq, heads, head_dim) throughout — "BSHD".
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, Hkv, D] -> [B, S, Hkv*n_rep, D] by head repetition (GQA)."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    q_offset: Optional[jax.Array] = None,
    kv_valid_len: Optional[jax.Array] = None,
) -> jax.Array:
    """Pure-XLA attention. Numerically the ground truth for the Pallas kernel tests.

    q: [B, Sq, H, D]; k/v: [B, Skv, Hkv, D]. Returns [B, Sq, H, D].
    `segment_ids`: [B, Skv] int array; attention only within equal segments (packing).
    `q_offset`: kv index of query row 0 (decode-with-cache); default aligns the ends.
    `kv_valid_len`: kv slots >= this are masked out (padded cache tail).
    """
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d**0.5)
    # f32 logits regardless of input dtype: MXU accumulates in f32 on TPU anyway.
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    sq, skv = q.shape[1], k.shape[1]
    kj = jnp.arange(skv)[None, :]
    if causal:
        if q_offset is None:
            q_offset = skv - sq
        qi = jnp.arange(sq)[:, None] + q_offset
        logits = jnp.where(kj <= qi, logits, -jnp.inf)
    if kv_valid_len is not None:
        logits = jnp.where(kj < kv_valid_len, logits, -jnp.inf)
    if segment_ids is not None:
        seg_q = segment_ids[:, -sq:]
        mask = seg_q[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(mask[:, None, :, :], logits, -jnp.inf)
    # Rows with no valid kv (fully masked) softmax to NaN; zero them instead.
    probs = jnp.nan_to_num(jax.nn.softmax(logits, axis=-1))
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    q_offset: Optional[jax.Array] = None,
    kv_valid_len: Optional[jax.Array] = None,
    impl: str = "auto",
) -> jax.Array:
    """Dispatching attention. impl: auto|pallas|reference.

    The Pallas path currently covers the training shape (no cache offsets, optional
    segment ids); decode-with-cache shapes use the XLA path, which fuses well anyway.
    """
    if impl == "auto":
        on_tpu = jax.default_backend() not in ("cpu", "gpu")
        # The pallas kernel's causal mask assumes query row i is absolute position i,
        # i.e. Sq == Skv; any offset/partial-window shape takes the XLA path.
        same_len = q.shape[1] == k.shape[1]
        # Mosaic tiles the lane (last) dim at 128 and sublanes at 8, and the
        # kernel requires seqs to be block-multiples once they exceed one
        # block: geometries the kernel can't tile (head_dim 16, seq 16, kv 20,
        # seq 520...) must fall back to XLA or TPU compile fails
        # ("slice shape must be aligned to tiling")
        def seq_ok(n: int, block: int) -> bool:
            return n % 8 == 0 and (n <= block or n % block == 0)

        from .flash_attention import DEFAULT_BLOCK_KV, DEFAULT_BLOCK_Q

        tileable = (q.shape[-1] % 128 == 0
                    and seq_ok(q.shape[1], DEFAULT_BLOCK_Q)
                    and seq_ok(k.shape[1], DEFAULT_BLOCK_KV))
        impl = (
            "pallas"
            if (on_tpu and tileable and q_offset is None and kv_valid_len is None
                and (same_len or not causal))
            else "reference"
        )
    if impl == "pallas":
        from .flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, segment_ids=segment_ids, scale=scale)
    return attention_reference(
        q,
        k,
        v,
        causal=causal,
        segment_ids=segment_ids,
        scale=scale,
        q_offset=q_offset,
        kv_valid_len=kv_valid_len,
    )
