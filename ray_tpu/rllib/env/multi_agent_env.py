"""MultiAgentEnv: dict-keyed multi-agent environment API.

Capability parity: reference rllib/env/multi_agent_env.py — reset() returns
(obs_dict, info_dict); step(action_dict) returns (obs, rewards, terminateds,
truncateds, infos) dicts keyed by agent id, with the special "__all__" key in
terminateds/truncateds signalling episode end; `make_multi_agent` wraps a
gymnasium env id into N independent agent copies (the reference's test/regression
workhorse).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple


class MultiAgentEnv:
    """Subclass and implement reset/step with dict-keyed agents."""

    possible_agents: List[Any] = []

    @property
    def agents(self) -> List[Any]:
        return list(self.possible_agents)

    def reset(self, *, seed: Optional[int] = None, options=None) -> Tuple[Dict, Dict]:
        raise NotImplementedError

    def step(self, action_dict: Dict[Any, Any]) -> Tuple[Dict, Dict, Dict, Dict, Dict]:
        raise NotImplementedError

    def observation_space_for(self, agent_id) -> Any:
        return self.observation_space[agent_id] if isinstance(self.observation_space, dict) else self.observation_space

    def action_space_for(self, agent_id) -> Any:
        return self.action_space[agent_id] if isinstance(self.action_space, dict) else self.action_space

    def close(self) -> None:
        pass


def make_multi_agent(env_name_or_maker) -> Callable[[Dict], MultiAgentEnv]:
    """N independent copies of a single-agent env as agents 0..N-1
    (reference rllib/env/multi_agent_env.py make_multi_agent)."""

    def maker(config: Optional[Dict] = None) -> MultiAgentEnv:
        config = dict(config or {})
        num = int(config.pop("num_agents", 2))

        def make_one():
            if callable(env_name_or_maker):
                return env_name_or_maker(config)
            import gymnasium as gym

            return gym.make(env_name_or_maker, **config)

        return _IndependentCopies([make_one() for _ in range(num)])

    return maker


class _IndependentCopies(MultiAgentEnv):
    def __init__(self, envs):
        self.envs = envs
        self.possible_agents = list(range(len(envs)))
        self.observation_space = envs[0].observation_space
        self.action_space = envs[0].action_space
        self._done = [False] * len(envs)

    def reset(self, *, seed=None, options=None):
        obs, infos = {}, {}
        for i, e in enumerate(self.envs):
            o, info = e.reset(seed=None if seed is None else seed + i, options=options)
            obs[i], infos[i] = o, info
            self._done[i] = False
        return obs, infos

    def step(self, action_dict):
        obs, rewards, terms, truncs, infos = {}, {}, {}, {}, {}
        for i, a in action_dict.items():
            if self._done[i]:
                continue
            o, r, te, tr, info = self.envs[i].step(a)
            obs[i], rewards[i], terms[i], truncs[i], infos[i] = o, r, te, tr, info
            if te or tr:
                self._done[i] = True
        terms["__all__"] = all(self._done)
        truncs["__all__"] = False
        return obs, rewards, terms, truncs, infos

    def close(self):
        for e in self.envs:
            e.close()
