"""EnvRunnerGroup: fault-tolerant set of rollout actors.

Capability parity: reference rllib/env/env_runner_group.py:71 — parallel sample(),
sync_weights from the learner group, restart of failed runners.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

from .env_runner import SingleAgentEnvRunner

import logging

logger = logging.getLogger("ray_tpu.rllib.env_runner_group")


class EnvRunnerGroup:
    def __init__(self, config: "AlgorithmConfig", runner_cls: type = None):  # noqa: F821
        runner_cls = runner_cls or SingleAgentEnvRunner
        self.config = config
        self.n = max(1, config.num_env_runners)
        self._actor_cls = ray_tpu.remote(num_cpus=1)(runner_cls)
        self.runners = [self._actor_cls.remote(config, i) for i in range(self.n)]
        self._last_weights_ref = None

    def sample(self, num_timesteps_total: Optional[int] = None, explore: bool = True):
        """Parallel sample; returns a merged episode list (single-agent) or a
        module_id -> episode-list dict (multi-agent runners)."""
        per = None
        if num_timesteps_total:
            per = max(1, num_timesteps_total // self.n)
        refs = [r.sample.remote(per, explore) for r in self.runners]
        episodes: List[Dict[str, np.ndarray]] = []
        by_module: Dict[str, List] = {}
        saw_dict = False
        for i, ref in enumerate(refs):
            try:
                res = ray_tpu.get(ref)
            except Exception as e:
                logger.warning("env runner %d died mid-sample (%r); "
                               "restarting it", i, e)
                self.restart_runner(i)
                continue
            if isinstance(res, dict):
                saw_dict = True
                for mid, eps in res.items():
                    by_module.setdefault(mid, []).extend(eps)
            else:
                episodes.extend(res)
        return by_module if saw_dict else episodes

    def restart_runner(self, i: int) -> None:
        """Replace a dead runner and replay the last weights (reference FT path)."""
        self.runners[i] = self._actor_cls.remote(self.config, i)
        if self._last_weights_ref is not None:
            self.runners[i].set_weights.remote(self._last_weights_ref)

    def sync_weights(self, weights) -> None:
        """Push inference weights to all runners (reference sync_weights)."""
        ref = ray_tpu.put(weights)
        self._last_weights_ref = ref
        ray_tpu.get([r.set_weights.remote(ref) for r in self.runners])

    def get_metrics(self) -> List[Dict[str, Any]]:
        out = []
        for r in self.runners:
            try:
                out.append(ray_tpu.get(r.get_metrics.remote()))
            # graftlint: allow[swallowed-exception] metrics from a dead runner are skipped; sampling restarts it elsewhere
            except Exception:
                pass
        return out

    def stop(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.get(r.stop.remote())
                ray_tpu.kill(r)
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass
