"""MultiAgentEnvRunner: rollout actor for MultiAgentEnv.

Capability parity: reference rllib/env/multi_agent_env_runner.py — steps one
MultiAgentEnv, batches per-module inference across the agents mapped to that
module (policy_mapping_fn), builds per-agent episodes, returns them grouped by
module id for the learner.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.rl_module import Columns, RLModuleSpec
from .episode import SingleAgentEpisode


class MultiAgentEpisode:
    """Per-agent SingleAgentEpisodes sharing one env episode (reference
    rllib/env/multi_agent_episode.py, append-as-you-step form)."""

    def __init__(self, agent_ids):
        self.agent_episodes: Dict[Any, SingleAgentEpisode] = {a: SingleAgentEpisode() for a in agent_ids}
        self.consumed_return = 0.0  # returns of per-agent chunks already handed to the learner

    def get_return(self) -> float:
        return self.consumed_return + float(sum(e.get_return() for e in self.agent_episodes.values()))


class MultiAgentEnvRunner:
    def __init__(self, config: "AlgorithmConfig", worker_index: int = 0):  # noqa: F821
        self.config = config
        self.worker_index = worker_index
        self.env = config.env_maker()()
        self.mapping_fn = config.policy_mapping_fn
        # one module per policy id, spaces from config.policies or env probe
        self.modules: Dict[str, Any] = {}
        self.params: Dict[str, Any] = {}
        for mid, spec in config.resolved_policy_specs(self.env).items():
            self.modules[mid] = spec.build()
            self.params[mid] = self.modules[mid].init_params(seed=(config.seed or 0))
        self.rng = np.random.default_rng((config.seed or 0) + worker_index + 1)
        self._obs: Optional[Dict] = None
        self._ma_episode: Optional[MultiAgentEpisode] = None
        self.metrics: Dict[str, Any] = {}

    # -- weights --------------------------------------------------------------
    def set_weights(self, params_by_mid: Dict[str, Any]) -> None:
        for mid, p in params_by_mid.items():
            self.params[mid] = p

    def get_state(self) -> Dict[str, Any]:
        return {"params": self.params}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]

    def ping(self) -> bool:
        return True

    # -- sampling -------------------------------------------------------------
    def _reset(self):
        obs, _ = self.env.reset(seed=int(self.rng.integers(1 << 30)))
        self._obs = obs
        self._ma_episode = MultiAgentEpisode(list(obs))
        for aid, o in obs.items():
            self._ma_episode.agent_episodes[aid].add_env_reset(o)

    def sample(self, num_timesteps: Optional[int] = None, explore: bool = True) -> Dict[str, List[Dict[str, np.ndarray]]]:
        """Rollout >= num_timesteps agent-steps; return episode dicts grouped by module."""
        num_timesteps = num_timesteps or self.config.rollout_fragment_length
        if self._obs is None:
            self._reset()
        out: Dict[str, List[Dict[str, np.ndarray]]] = {mid: [] for mid in self.modules}
        returns: List[float] = []
        steps = 0
        while steps < num_timesteps:
            # group live agents by module for batched inference
            by_mid: Dict[str, List[Any]] = {}
            for aid in self._obs:
                by_mid.setdefault(self.mapping_fn(aid), []).append(aid)
            actions: Dict[Any, Any] = {}
            extras: Dict[Any, Dict] = {}
            for mid, aids in by_mid.items():
                module = self.modules[mid]
                obs_b = np.stack([np.asarray(self._obs[a], np.float32).reshape(-1) for a in aids])
                mout = module.apply_np(self.params[mid], obs_b)
                dist = module.action_dist_cls
                di = mout[Columns.ACTION_DIST_INPUTS]
                acts = dist.sample_np(di, self.rng) if explore else dist.greedy_np(di)
                logp = dist.logp_np(di, acts)
                for j, a in enumerate(aids):
                    actions[a] = acts[j]
                    extras[a] = {Columns.ACTION_LOGP: logp[j], Columns.VF_PREDS: mout[Columns.VF_PREDS][j]}
            obs, rewards, terms, truncs, _ = self.env.step(actions)
            for aid in actions:
                if aid not in rewards:
                    continue
                ep = self._ma_episode.agent_episodes[aid]
                done_a = bool(terms.get(aid, False)) or bool(truncs.get(aid, False))
                nxt = obs.get(aid, self._obs[aid])
                ep.add_env_step(nxt, actions[aid], rewards[aid], terms.get(aid, False),
                                truncs.get(aid, False), extra=extras[aid])
                steps += 1
                if done_a:
                    out[self.mapping_fn(aid)].append(ep.to_numpy())
                    self._ma_episode.consumed_return += ep.get_return()
                    self._ma_episode.agent_episodes[aid] = SingleAgentEpisode()  # consumed
            if terms.get("__all__") or truncs.get("__all__"):
                returns.append(self._ma_episode.get_return())
                self._reset()
            else:
                self._obs = {a: o for a, o in obs.items()}
                # agents may join mid-episode (turn-based / spawn envs), or a
                # consumed (done) agent id may re-spawn with a fresh episode
                for aid, o in self._obs.items():
                    ep = self._ma_episode.agent_episodes.get(aid)
                    if ep is None or not ep.observations:
                        ep = SingleAgentEpisode()
                        ep.add_env_reset(o)
                        self._ma_episode.agent_episodes[aid] = ep
        # flush in-progress agent chunks (bootstrap from their last obs)
        for aid, ep in self._ma_episode.agent_episodes.items():
            if len(ep):
                out[self.mapping_fn(aid)].append(ep.to_numpy())
                self._ma_episode.consumed_return += ep.get_return()
                last_obs = ep.observations[-1]
                self._ma_episode.agent_episodes[aid] = SingleAgentEpisode()
                self._ma_episode.agent_episodes[aid].add_env_reset(last_obs)
        self.metrics = {
            "num_env_steps_sampled": steps,
            "episode_return_mean": float(np.mean(returns)) if returns else None,
            "num_episodes": len(returns),
        }
        return out

    def get_metrics(self) -> Dict[str, Any]:
        return self.metrics

    def stop(self) -> None:
        try:
            self.env.close()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
