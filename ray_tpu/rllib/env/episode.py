"""SingleAgentEpisode: one (chunk of an) env trajectory.

Capability parity: reference rllib/env/single_agent_episode.py — append-as-you-step
storage, terminated/truncated flags, extra model outputs (logp, vf), numpy conversion.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class SingleAgentEpisode:
    observations: List[np.ndarray] = dataclasses.field(default_factory=list)  # T+1
    actions: List[np.ndarray] = dataclasses.field(default_factory=list)  # T
    rewards: List[float] = dataclasses.field(default_factory=list)  # T
    terminated: bool = False
    truncated: bool = False
    extra_model_outputs: Dict[str, List] = dataclasses.field(default_factory=dict)

    def add_env_reset(self, obs) -> None:
        self.observations.append(np.asarray(obs))

    def add_env_step(self, obs, action, reward, terminated=False, truncated=False, extra: Optional[Dict] = None) -> None:
        self.observations.append(np.asarray(obs))
        self.actions.append(np.asarray(action))
        self.rewards.append(float(reward))
        self.terminated = bool(terminated)
        self.truncated = bool(truncated)
        for k, v in (extra or {}).items():
            self.extra_model_outputs.setdefault(k, []).append(v)

    def __len__(self) -> int:
        return len(self.actions)

    @property
    def is_done(self) -> bool:
        return self.terminated or self.truncated

    def get_return(self) -> float:
        return float(sum(self.rewards))

    def to_numpy(self) -> Dict[str, np.ndarray]:
        out = {
            "obs": np.stack(self.observations[:-1]),
            "next_obs_last": np.asarray(self.observations[-1]),
            "actions": np.stack(self.actions),
            "rewards": np.asarray(self.rewards, np.float32),
            "terminated": self.terminated,
            "truncated": self.truncated,
        }
        for k, v in self.extra_model_outputs.items():
            out[k] = np.asarray(v)
        return out
