"""SingleAgentEnvRunner: the rollout actor.

Capability parity: reference rllib/env/single_agent_env_runner.py:68 (sample at :147) —
gymnasium vector env stepping, exploration via the module's action distribution,
episode chunking on rollout_fragment_length, weight sync via set_state.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core.rl_module import Columns, RLModuleSpec
from .episode import SingleAgentEpisode


class SingleAgentEnvRunner:
    def __init__(self, config: "AlgorithmConfig", worker_index: int = 0):  # noqa: F821
        import gymnasium as gym

        self.config = config
        self.worker_index = worker_index
        self.num_envs = config.num_envs_per_env_runner
        maker = config.env_maker()
        # envs that expose a natively-vectorized constructor (classmethod
        # make_vec(num_envs, config) -> object with reset/step/close batched
        # over envs) skip SyncVectorEnv's per-env Python step loop
        if isinstance(config.env, type) and hasattr(config.env, "make_vec"):
            self.env = config.env.make_vec(self.num_envs, dict(config.env_config))
        else:
            self.env = gym.vector.SyncVectorEnv([maker for _ in range(self.num_envs)])
        single_env = maker()
        self.module = RLModuleSpec(
            module_class=config.rl_module_class,
            observation_space=single_env.observation_space,
            action_space=single_env.action_space,
            model_config=config.model_config,
        ).build()
        single_env.close()
        self.params = self.module.init_params(seed=config.seed or 0)
        self.rng = np.random.default_rng((config.seed or 0) + worker_index + 1)
        self._episodes: List[SingleAgentEpisode] = []
        self._obs = None
        self.metrics: Dict[str, Any] = {}

    # -- weights --------------------------------------------------------------
    def get_state(self) -> Dict[str, Any]:
        return {"params": self.params}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]

    def set_weights(self, params) -> None:
        self.params = params

    def ping(self) -> bool:
        return True

    # -- sampling -------------------------------------------------------------
    def _reset_if_needed(self):
        if self._obs is None:
            obs, _ = self.env.reset(seed=(self.config.seed or 0) + self.worker_index)
            self._obs = obs
            self._episodes = [SingleAgentEpisode() for _ in range(self.num_envs)]
            self._prev_done = np.zeros(self.num_envs, dtype=bool)
            for i in range(self.num_envs):
                self._episodes[i].add_env_reset(obs[i])

    def sample(
        self,
        num_timesteps: Optional[int] = None,
        explore: bool = True,
    ) -> List[Dict[str, np.ndarray]]:
        """Roll out >= num_timesteps env steps; return finished+chunked episodes as dicts."""
        num_timesteps = num_timesteps or self.config.rollout_fragment_length * self.num_envs
        self._reset_if_needed()
        done_eps: List[SingleAgentEpisode] = []
        steps = 0
        dist = self.module.action_dist_cls
        returns: List[float] = []
        while steps < num_timesteps:
            out = self.module.forward_exploration(self.params, {Columns.OBS: self._obs})
            dist_inputs = out[Columns.ACTION_DIST_INPUTS]
            if explore:
                actions = dist.sample_np(dist_inputs, self.rng)
            else:
                actions = dist.greedy_np(dist_inputs)
            logp = dist.logp_np(dist_inputs, actions)
            obs, rewards, terms, truncs, _ = self.env.step(actions)
            for i in range(self.num_envs):
                if self._prev_done[i]:
                    # gymnasium 1.x next-step autoreset: this step reset env i and
                    # ignored the action — obs[i] is the new episode's first obs.
                    self._episodes[i] = SingleAgentEpisode()
                    self._episodes[i].add_env_reset(obs[i])
                    self._prev_done[i] = False
                    continue
                ep = self._episodes[i]
                ep.add_env_step(
                    obs[i], actions[i], rewards[i], terms[i], truncs[i],
                    extra={
                        Columns.ACTION_LOGP: logp[i],
                        Columns.VF_PREDS: out[Columns.VF_PREDS][i],
                    },
                )
                steps += 1
                if terms[i] or truncs[i]:
                    returns.append(ep.get_return())
                    done_eps.append(ep)
                    self._prev_done[i] = True
            self._obs = obs
        # flush in-progress chunks (not done -> learner bootstraps from next_obs_last)
        for i in range(self.num_envs):
            if not self._prev_done[i] and len(self._episodes[i]):
                done_eps.append(self._episodes[i])
                self._episodes[i] = SingleAgentEpisode()
                self._episodes[i].add_env_reset(self._obs[i])
        self.metrics = {
            "num_env_steps_sampled": steps,
            "episode_return_mean": float(np.mean(returns)) if returns else None,
            "num_episodes": len(returns),
        }
        return [ep.to_numpy() for ep in done_eps]

    def get_metrics(self) -> Dict[str, Any]:
        return self.metrics

    def stop(self) -> None:
        try:
            self.env.close()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
