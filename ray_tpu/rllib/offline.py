"""Offline RL data plane: OfflineData + OfflinePreLearner.

Capability parity: reference rllib/offline/offline_data.py:30 (OfflineData — sample
batches out of a ray.data Dataset of recorded transitions) and offline_prelearner.py:55
(OfflinePreLearner — map raw rows to learner-ready train batches, computing returns).
Storage rides ray_tpu.data (parquet/json), mirroring the reference's Ray Data reader.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .core.rl_module import Columns


# canonical transition columns (reference SampleBatch / offline schema)
SCHEMA = ("obs", "actions", "rewards", "next_obs", "dones", "eps_id")


def episodes_to_rows(episodes: List[Dict[str, np.ndarray]], start_eps_id: int = 0) -> List[Dict[str, Any]]:
    """Flatten env-runner episode dicts into one row per transition (for recording)."""
    rows: List[Dict[str, Any]] = []
    for eid, ep in enumerate(episodes, start=start_eps_id):
        T = len(ep["rewards"])
        obs = np.asarray(ep["obs"], np.float32).reshape(T, -1)
        nxt = np.concatenate([obs[1:], np.asarray(ep["next_obs_last"], np.float32).reshape(1, -1)])
        for t in range(T):
            rows.append({
                "obs": obs[t].tolist(),
                "actions": np.asarray(ep["actions"][t]).tolist(),
                "rewards": float(ep["rewards"][t]),
                "next_obs": nxt[t].tolist(),
                "dones": bool((ep["terminated"]) and t == T - 1),
                "eps_id": int(eid),
                "t": t,
            })
    return rows


class OfflinePreLearner:
    """Rows -> learner batch: groups by episode, adds discounted return-to-go."""

    def __init__(self, gamma: float):
        self.gamma = gamma

    def __call__(self, rows: List[Dict[str, Any]]) -> Dict[str, np.ndarray]:
        by_ep: Dict[int, List[Dict[str, Any]]] = {}
        for r in rows:
            by_ep.setdefault(int(r.get("eps_id", 0)), []).append(r)
        obs, actions, rewards, next_obs, dones, rtg = [], [], [], [], [], []
        for _, ep_rows in sorted(by_ep.items()):
            ep_rows.sort(key=lambda r: r.get("t", 0))
            g = 0.0
            ep_rtg = np.zeros(len(ep_rows), np.float32)
            for i in range(len(ep_rows) - 1, -1, -1):
                g = float(ep_rows[i]["rewards"]) + self.gamma * g
                ep_rtg[i] = g
            for i, r in enumerate(ep_rows):
                obs.append(np.asarray(r["obs"], np.float32))
                actions.append(np.asarray(r["actions"]))
                rewards.append(float(r["rewards"]))
                next_obs.append(np.asarray(r["next_obs"], np.float32))
                dones.append(float(bool(r["dones"])))
                rtg.append(ep_rtg[i])
        return {
            Columns.OBS: np.stack(obs),
            Columns.ACTIONS: np.stack(actions),
            "rewards": np.asarray(rewards, np.float32),
            "next_obs": np.stack(next_obs),
            "dones": np.asarray(dones, np.float32),
            "returns_to_go": np.asarray(rtg, np.float32),
        }


class OfflineData:
    """Materialized offline dataset with random minibatch sampling."""

    def __init__(self, config: "AlgorithmConfig", dataset=None):  # noqa: F821
        from ray_tpu import data as rtd

        if dataset is not None or config.input_dataset is not None:
            ds = dataset if dataset is not None else config.input_dataset
        else:
            paths = config.input_
            first = paths[0] if isinstance(paths, (list, tuple)) else paths
            if isinstance(first, str) and first.endswith(".json"):
                ds = rtd.read_json(paths)
            else:
                ds = rtd.read_parquet(paths)
        pre = OfflinePreLearner(config.gamma)
        self.batch = pre(ds.take_all())
        self.n = len(self.batch[Columns.OBS])

    def __len__(self) -> int:
        return self.n

    def sample(self, batch_size: int, rng: np.random.Generator) -> Dict[str, np.ndarray]:
        idx = rng.integers(0, self.n, size=min(batch_size, self.n))
        return {k: v[idx] for k, v in self.batch.items()}
