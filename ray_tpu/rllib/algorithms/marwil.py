"""MARWIL: monotonic advantage re-weighted imitation learning (+ BC as beta=0).

Capability parity: reference rllib/algorithms/marwil/ — exponentially
advantage-weighted behavior cloning with a learned value baseline; the reference's
BC algorithm is literally MARWIL with beta=0 (rllib/algorithms/bc/bc.py), mirrored
here. Offline input via OfflineData (parquet/json through ray_tpu.data).
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..core.learner import Learner
from ..core.rl_module import Columns
from ..offline import OfflineData
from .algorithm import Algorithm
from .algorithm_config import AlgorithmConfig


class MARWILConfig(AlgorithmConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or MARWIL)
        self.beta: float = 1.0  # 0 => plain behavior cloning
        self.vf_coeff: float = 1.0
        self.moving_average_sqd_adv_norm_update_rate: float = 1e-8  # kept for API parity
        self.num_updates_per_iteration: int = 32
        self.train_batch_size = 512
        self.num_epochs = 1

    def training(self, *, beta=None, vf_coeff=None, num_updates_per_iteration=None, **kwargs):
        for k, v in dict(beta=beta, vf_coeff=vf_coeff,
                         num_updates_per_iteration=num_updates_per_iteration).items():
            if v is not None:
                setattr(self, k, v)
        super().training(**kwargs)
        return self


class MARWILLearner(Learner):
    def compute_losses(self, params, batch):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        out = self.module.forward_train(params, batch)
        dist = self.module.action_dist_cls
        logp = dist.logp_jax(out[Columns.ACTION_DIST_INPUTS], batch[Columns.ACTIONS])
        vf = out[Columns.VF_PREDS]
        rtg = batch["returns_to_go"]
        vf_loss = jnp.mean(jnp.square(vf - rtg))
        if cfg.beta > 0.0:
            adv = jax.lax.stop_gradient(rtg - vf)
            # normalize by the batch RMS advantage (reference keeps a moving average)
            adv = adv / jnp.maximum(jnp.sqrt(jnp.mean(jnp.square(adv))), 1e-6)
            weights = jnp.minimum(jnp.exp(cfg.beta * adv), 20.0)
        else:
            weights = 1.0
        policy_loss = -jnp.mean(weights * logp)
        total = policy_loss + cfg.vf_coeff * vf_loss * (1.0 if cfg.beta > 0.0 else 0.0)
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "mean_logp": jnp.mean(logp)}


class MARWIL(Algorithm):
    learner_class = MARWILLearner

    @classmethod
    def get_default_config(cls) -> MARWILConfig:
        return MARWILConfig(cls)

    def setup(self, _config) -> None:
        cfg = self._algo_config
        # keep the materialized dataset off the config so actors don't get copies
        ds, cfg.input_dataset = cfg.input_dataset, None
        super().setup(_config)
        self.offline_data = OfflineData(cfg, dataset=ds)
        self._rng = np.random.default_rng(cfg.seed or 0)

    def training_step(self) -> Dict[str, Any]:
        cfg = self._algo_config
        for _ in range(cfg.num_updates_per_iteration):
            batch = self.offline_data.sample(cfg.train_batch_size, self._rng)
            for lm in self.learner_group.update(batch):
                self.metrics.log_dict(lm)
        if self.env_runner_group is not None:
            self.env_runner_group.sync_weights(self.learner_group.get_weights())
        return self.metrics.reduce()


class BCConfig(MARWILConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or BC)
        self.beta = 0.0


class BC(MARWIL):
    """Behavior cloning (reference rllib/algorithms/bc/bc.py: MARWIL with beta=0)."""

    @classmethod
    def get_default_config(cls) -> BCConfig:
        return BCConfig(cls)
