"""CQL: conservative Q-learning for offline continuous control.

Capability parity: reference rllib/algorithms/cql/ — SAC's twin-Q losses plus the
CQL(H) conservative regularizer (importance-sampled logsumexp of Q over random +
policy actions minus Q on dataset actions, Kumar et al. 2020) and `bc_iters`
warm-start (actor imitates the dataset before switching to the Q-maximizing loss).
Offline input via OfflineData; no env runners.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..offline import OfflineData
from .sac import SAC, SACConfig, SACLearner


class CQLConfig(SACConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or CQL)
        self.min_q_weight: float = 5.0
        self.num_cql_actions: int = 4  # sampled actions per logsumexp branch
        self.bc_iters: int = 200
        self.num_updates_per_iteration = 64

    def training(self, *, min_q_weight=None, num_cql_actions=None, bc_iters=None, **kwargs):
        for k, v in dict(min_q_weight=min_q_weight, num_cql_actions=num_cql_actions,
                         bc_iters=bc_iters).items():
            if v is not None:
                setattr(self, k, v)
        super().training(**kwargs)
        return self


class CQLLearner(SACLearner):
    def build(self) -> None:
        super().build()
        self._num_updates = 0

    def _build_update_fn(self):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        module = self.module

        def q_many(params, which, obs, actions_n):
            """Q over N actions per state: obs [B,D], actions_n [N,B,A] -> [N,B]."""
            N = actions_n.shape[0]
            B = obs.shape[0]
            obs_rep = jnp.broadcast_to(obs[None], (N,) + obs.shape).reshape(N * B, -1)
            q = module.q_jax(params, which, obs_rep, actions_n.reshape(N * B, -1))
            return q.reshape(N, B)

        def loss_fn(params, target_params, batch, rng, target_ent, use_bc):
            sg = jax.lax.stop_gradient
            sg_tree = lambda t: jax.tree_util.tree_map(sg, t)  # noqa: E731
            r1, r2, r3, r4, r5 = jax.random.split(rng, 5)
            alpha = jnp.exp(params["log_alpha"])
            B = batch["obs"].shape[0]
            N = cfg.num_cql_actions
            A = module.act_dim

            # --- standard SAC critic targets ---
            next_a, next_logp = module.sample_action_jax(sg_tree(params), batch["next_obs"], r1)
            tq1 = module.q_jax(target_params, "q1", batch["next_obs"], next_a)
            tq2 = module.q_jax(target_params, "q2", batch["next_obs"], next_a)
            target_v = jnp.minimum(tq1, tq2) - sg(alpha) * next_logp
            target = sg(batch["rewards"] + cfg.gamma * (1.0 - batch["dones"]) * target_v)
            q1 = module.q_jax(params, "q1", batch["obs"], batch["actions"])
            q2 = module.q_jax(params, "q2", batch["obs"], batch["actions"])
            bellman = jnp.mean((q1 - target) ** 2) + jnp.mean((q2 - target) ** 2)

            # --- CQL(H) conservative regularizer ---
            low, high = jnp.asarray(module.low), jnp.asarray(module.high)
            rand_a = jax.random.uniform(r2, (N, B, A), minval=low, maxval=high)
            rand_logp = -jnp.sum(jnp.log(high - low))  # uniform density over the box

            def pi_actions(rng_, obs):
                obs_rep = jnp.broadcast_to(obs[None], (N,) + obs.shape).reshape(N * B, -1)
                a, lp = module.sample_action_jax(sg_tree(params), obs_rep, rng_)
                return a.reshape(N, B, A), lp.reshape(N, B)

            cur_a, cur_lp = pi_actions(r3, batch["obs"])
            nxt_a, nxt_lp = pi_actions(r4, batch["next_obs"])

            def conservative(which, q_data):
                q_rand = q_many(params, which, batch["obs"], rand_a) - rand_logp
                q_cur = q_many(params, which, batch["obs"], cur_a) - sg(cur_lp)
                q_nxt = q_many(params, which, batch["obs"], nxt_a) - sg(nxt_lp)
                stacked = jnp.concatenate([q_rand, q_cur, q_nxt], axis=0)  # [3N, B]
                return jnp.mean(jax.scipy.special.logsumexp(stacked, axis=0) - q_data)

            cql_term = conservative("q1", q1) + conservative("q2", q2)
            critic_loss = bellman + cfg.min_q_weight * cql_term

            # --- actor: BC warm-start, then SAC objective ---
            frozen = {**params, "q1": sg_tree(params["q1"]), "q2": sg_tree(params["q2"])}
            a_new, logp = module.sample_action_jax(params, batch["obs"], r5)
            q_pi = jnp.minimum(module.q_jax(frozen, "q1", batch["obs"], a_new),
                               module.q_jax(frozen, "q2", batch["obs"], a_new))
            sac_actor = jnp.mean(sg(alpha) * logp - q_pi)
            # BC: maximize logp of the dataset action under the squashed gaussian
            mu, log_std = module.pi_jax(params, batch["obs"])
            # invert the squash to score dataset actions (clip to the open interval)
            t = jnp.clip((batch["actions"] - low) / (high - low) * 2.0 - 1.0, -0.999, 0.999)
            u = jnp.arctanh(t)
            from ..core.distributions import squashed_logp_from_u_jax

            data_logp = squashed_logp_from_u_jax(u, t, mu, log_std, low, high)
            bc_actor = jnp.mean(sg(alpha) * logp - data_logp)
            actor_loss = jnp.where(use_bc, bc_actor, sac_actor)

            alpha_loss = -jnp.mean(params["log_alpha"] * sg(logp + target_ent))
            total = critic_loss + actor_loss + alpha_loss
            aux = {"critic_loss": critic_loss, "actor_loss": actor_loss,
                   "cql_loss": cql_term, "alpha": alpha, "mean_q": jnp.mean(q1)}
            return total, aux

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        @jax.jit
        def update(params, target_params, batch, rng, target_ent, use_bc):
            (loss, aux), grads = grad_fn(params, target_params, batch, rng, target_ent, use_bc)
            return loss, aux, grads

        return update

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        import jax
        import optax

        self._rng, sub = jax.random.split(self._rng)
        use_bc = np.bool_(self._num_updates < self.config.bc_iters)
        loss, aux, grads = self._update_fn(self.params, self.target_params, batch,
                                           sub, self._target_entropy, use_bc)
        grads = self._sync_grads(grads)
        updates, self.opt_state = self.optimizer.update(grads, self.opt_state, self.params)
        self.params = optax.apply_updates(self.params, updates)
        self.params = jax.tree_util.tree_map(np.asarray, self.params)
        tau = self.config.tau
        for which in ("q1", "q2"):
            self.target_params[which] = jax.tree_util.tree_map(
                lambda t, p: np.asarray((1 - tau) * t + tau * p),
                self.target_params[which], self.params[which])
        self._num_updates += 1
        self.metrics = {"total_loss": float(loss),
                        **{k: float(v) for k, v in aux.items()}}
        return self.metrics

    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        state["num_updates"] = self._num_updates
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        # restore the update counter so a resumed run doesn't redo BC warm-start
        self._num_updates = int(state.get("num_updates", self.config.bc_iters))


class CQL(SAC):
    learner_class = CQLLearner

    @classmethod
    def get_default_config(cls) -> CQLConfig:
        return CQLConfig(cls)

    def setup(self, _config) -> None:
        from .algorithm import Algorithm

        cfg = self._algo_config
        # keep the materialized dataset off the config so actors don't get copies
        ds, cfg.input_dataset = cfg.input_dataset, None
        # skip SAC.setup: offline CQL has no replay buffer or env-step accounting
        Algorithm.setup(self, _config)
        self._rng = np.random.default_rng(cfg.seed or 0)
        self._env_steps = 0  # SAC.save_checkpoint expects it
        self.offline_data = OfflineData(cfg, dataset=ds)

    def training_step(self) -> Dict[str, Any]:
        cfg = self._algo_config
        for _ in range(cfg.num_updates_per_iteration):
            batch = self.offline_data.sample(cfg.train_batch_size, self._rng)
            for lm in self.learner_group.update(batch):
                self.metrics.log_dict(lm)
        return self.metrics.reduce()
