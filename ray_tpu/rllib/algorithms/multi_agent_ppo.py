"""MultiAgentPPO: independent PPO per policy module over a MultiAgentEnv.

Capability parity: reference rllib's multi-agent new API stack (PPO +
MultiRLModule + MultiAgentEnvRunner + policy_mapping_fn). Each policy id gets
its own params/optimizer (MultiAgentLearner); rollouts come back grouped by
module; GAE and the PPO update run per module.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..connectors import GeneralAdvantageEstimation
from ..core.multi_learner import MultiAgentLearner
from ..core.learner_group import LearnerGroup
from ..env.env_runner_group import EnvRunnerGroup
from ..env.multi_agent_env_runner import MultiAgentEnvRunner
from ..utils.metrics_logger import MetricsLogger
from .ppo import PPO, PPOConfig, PPOLearner


class MultiAgentPPOConfig(PPOConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or MultiAgentPPO)


class MultiAgentPPO(PPO):
    learner_class = MultiAgentLearner

    @classmethod
    def get_default_config(cls) -> MultiAgentPPOConfig:
        return MultiAgentPPOConfig(cls)

    def setup(self, _config) -> None:
        from ray_tpu.usage import record_library_usage

        record_library_usage("rllib")
        cfg = self._algo_config
        if not cfg.is_multi_agent:
            cfg.multi_agent(policies=["default_policy"])
        cfg.base_learner_class = type(self).base_learner_class
        self.metrics = MetricsLogger()
        probe = cfg.env_maker()()
        self.module_specs = cfg.resolved_policy_specs(probe)
        probe.close()
        self.env_runner_group = EnvRunnerGroup(cfg, runner_cls=MultiAgentEnvRunner)
        self.learner_group = LearnerGroup(cfg, self.module_specs, self.learner_class)
        # host-side module copies for GAE bootstrap values
        self._modules = {mid: spec.build() for mid, spec in self.module_specs.items()}
        self._gae = GeneralAdvantageEstimation(cfg.gamma, cfg.lambda_)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

    base_learner_class = PPOLearner

    def training_step(self) -> Dict[str, Any]:
        cfg = self._algo_config
        samples: Dict[str, list] = self.env_runner_group.sample(cfg.train_batch_size)
        if not samples or not any(samples.values()):
            return self.metrics.reduce()
        for m in self.env_runner_group.get_metrics():
            self.metrics.log_dict({k: v for k, v in m.items() if v is not None}, window=20)
        params = self.learner_group.get_weights()
        batches = {
            mid: self._gae(eps, module=self._modules[mid], params=params[mid])
            for mid, eps in samples.items() if eps
        }
        learner_metrics = self.learner_group.update(batches)
        for lm in learner_metrics:
            for mid, m in lm.items():
                self.metrics.log_dict({f"{mid}/{k}": v for k, v in m.items()})
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        result = self.metrics.reduce()
        result["num_env_steps_trained"] = int(sum(
            len(b["obs"]) for b in batches.values()))
        return result

    def evaluate(self, num_timesteps: int = 1000) -> Dict[str, Any]:
        self.env_runner_group.sample(num_timesteps, explore=False)
        rets = [m.get("episode_return_mean") for m in self.env_runner_group.get_metrics()
                if m.get("episode_return_mean") is not None]
        return {"evaluation": {"episode_return_mean": float(np.mean(rets)) if rets else None}}
