"""APPO: asynchronous PPO — IMPALA's architecture with PPO's clipped surrogate.

Capability parity: reference rllib/algorithms/appo/appo.py — async env-runner
sampling + V-trace advantages (inherited from IMPALA) with the policy loss swapped
for the PPO clip objective against the behaviour policy (the "old" policy in APPO
is the policy that generated the rollout, so no separate target net is needed for
the surrogate). `use_kl_loss` adds the adaptive KL penalty: after each update the
coefficient is doubled/halved toward `kl_target` (reference appo.py
update_kl / after_train_step).
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from .impala import IMPALA, IMPALAConfig, IMPALALearner


class APPOConfig(IMPALAConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or APPO)
        self.clip_param: float = 0.4
        self.use_kl_loss: bool = False
        self.kl_coeff: float = 0.2
        self.kl_target: float = 0.01

    def training(self, *, clip_param=None, use_kl_loss=None, kl_coeff=None, kl_target=None, **kwargs):
        for k, v in dict(clip_param=clip_param, use_kl_loss=use_kl_loss,
                         kl_coeff=kl_coeff, kl_target=kl_target).items():
            if v is not None:
                setattr(self, k, v)
        super().training(**kwargs)
        return self


class APPOLearner(IMPALALearner):
    def build(self) -> None:
        super().build()
        self._kl_coeff = float(self.config.kl_coeff)

    def _pg_loss(self, target_logp, behaviour_logp, pg_adv, mask, n, kl_coeff):
        import jax.numpy as jnp

        cfg = self.config
        ratio = jnp.exp(target_logp - behaviour_logp) * mask
        surr1 = ratio * pg_adv
        surr2 = jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param) * pg_adv
        loss = -(jnp.minimum(surr1, surr2)).sum() / n
        if cfg.use_kl_loss:
            kl = ((behaviour_logp - target_logp) * mask).sum() / n
            loss = loss + kl_coeff * kl
        return loss

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        cfg = self.config
        if cfg.use_kl_loss:
            batch = {**batch, "kl_coeff": np.float32(self._kl_coeff)}
        metrics = super().update(batch)
        if cfg.use_kl_loss:
            # adaptive coefficient (reference appo update_kl): 2x above, 0.5x below
            kl = metrics.get("mean_kl", 0.0)
            if kl > 2.0 * cfg.kl_target:
                self._kl_coeff *= 1.5
            elif kl < 0.5 * cfg.kl_target:
                self._kl_coeff *= 0.5
            metrics["kl_coeff"] = self._kl_coeff
        return metrics

    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        state["kl_coeff"] = self._kl_coeff
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        if state.get("kl_coeff") is not None:
            self._kl_coeff = float(state["kl_coeff"])


class APPO(IMPALA):
    learner_class = APPOLearner

    @classmethod
    def get_default_config(cls) -> APPOConfig:
        return APPOConfig(cls)
