"""DreamerV3: world-model RL — RSSM + actor-critic trained in imagination.

Capability parity: reference rllib/algorithms/dreamerv3/ (dreamerv3.py;
torch world-model/actor/critic in dreamerv3/torch/, custom recurrent env
runner in dreamerv3/utils/env_runner.py). JAX-first here: the world model
(encoder → RSSM with categorical latents → decoder/reward/continue heads),
imagination rollouts, and both actor and critic updates are single jitted
programs over scanned sequences.

Key mechanisms kept from the paper/reference:
- RSSM: GRU deterministic path; stochastic state = K categorical distributions
  of C classes with straight-through sampling and 1% uniform mixing (unimix);
- KL balancing with free bits: beta_dyn * max(1, KL(sg(post) || prior)) +
  beta_rep * max(1, KL(post || sg(prior)));
- symlog regression for reconstruction/reward/value;
- imagination: H-step rollouts from replayed posterior states, lambda-returns,
  EMA-regularized critic, REINFORCE actor with return normalization by an EMA
  of the 5th..95th return percentile range;
- replay: one contiguous step stream with is_first markers (windows may span
  episode boundaries; the RSSM resets where is_first=1).

The reference ships its own recurrent env runner because acting needs the
(h, z) state; DreamerV3EnvRunner mirrors that.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.learner import Learner
from .algorithm import Algorithm
from .algorithm_config import AlgorithmConfig


# ------------------------------------------------------------------ jax helpers

def _symlog(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def _symexp(x):
    import jax.numpy as jnp

    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def _linear(rng: np.random.Generator, n_in: int, n_out: int) -> Dict[str, np.ndarray]:
    scale = np.sqrt(2.0 / max(1, n_in))
    return {"w": (rng.standard_normal((n_in, n_out)) * scale).astype(np.float32),
            "b": np.zeros((n_out,), np.float32)}


def _mlp_params(rng, sizes) -> List[Dict[str, np.ndarray]]:
    return [_linear(rng, a, b) for a, b in zip(sizes[:-1], sizes[1:])]


def _mlp(params, x, final_linear=True):
    import jax.numpy as jnp

    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or not final_linear:
            x = jnp.tanh(x)
    return x


class DreamerV3Config(AlgorithmConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or DreamerV3)
        # model sizes (toy-scale defaults; the 8B-scale knobs are the same names)
        self.deter_size: int = 128
        self.stoch_classes: int = 8  # C
        self.stoch_groups: int = 8   # K -> z is K*C one-hots
        self.hidden: int = 128
        self.embed_size: int = 128
        # replay / training schedule
        self.replay_capacity: int = 100_000
        self.batch_size_seqs: int = 16
        self.seq_len: int = 16
        self.num_updates_per_iteration: int = 8
        self.sample_timesteps_per_iteration: int = 400
        self.num_steps_sampled_before_learning_starts: int = 1000
        # losses
        self.beta_pred: float = 1.0
        self.beta_dyn: float = 0.5
        self.beta_rep: float = 0.1
        self.free_bits: float = 1.0
        self.unimix: float = 0.01
        # imagination / actor-critic
        self.imag_horizon: int = 15
        self.gamma = 0.99
        self.lambda_: float = 0.95
        self.entropy_coef: float = 3e-3
        self.critic_ema_decay: float = 0.98
        self.retnorm_decay: float = 0.99
        self.lr_world: float = 4e-4
        self.lr_actor: float = 1e-4
        self.lr_critic: float = 1e-4
        self.grad_clip = 100.0

    def training(self, **kwargs) -> "DreamerV3Config":
        known = {k: kwargs.pop(k) for k in list(kwargs)
                 if hasattr(self, k) and k not in AlgorithmConfig.__dict__}
        for k, v in known.items():
            if v is not None:
                setattr(self, k, v)
        super().training(**kwargs)
        return self


# ----------------------------------------------------------------- model (pure)

class _DreamerNets:
    """Pure-jax parameter builders + apply fns (no framework Modules)."""

    def __init__(self, obs_dim: int, n_actions: int, cfg: DreamerV3Config):
        self.obs_dim = obs_dim
        self.n_actions = n_actions
        self.cfg = cfg
        self.z_size = cfg.stoch_groups * cfg.stoch_classes

    def init_params(self, seed: int = 0) -> Dict[str, Any]:
        cfg = self.cfg
        rng = np.random.default_rng(seed)
        d, h, e, z, a = (cfg.deter_size, cfg.hidden, cfg.embed_size,
                         self.z_size, self.n_actions)
        return {
            "enc": _mlp_params(rng, [self.obs_dim, h, e]),
            # GRU over x=[z, a_onehot] with state h
            "gru_r": _linear(rng, z + a + d, d),
            "gru_u": _linear(rng, z + a + d, d),
            "gru_c": _linear(rng, z + a + d, d),
            "prior": _mlp_params(rng, [d, h, z]),
            "post": _mlp_params(rng, [d + e, h, z]),
            "dec": _mlp_params(rng, [d + z, h, self.obs_dim]),
            "rew": _mlp_params(rng, [d + z, h, 1]),
            "cont": _mlp_params(rng, [d + z, h, 1]),
            "actor": _mlp_params(rng, [d + z, h, a]),
            "critic": _mlp_params(rng, [d + z, h, 1]),
        }

    # -- rssm -------------------------------------------------------------
    def gru(self, p, hstate, z, a_onehot):
        import jax
        import jax.numpy as jnp

        x = jnp.concatenate([z, a_onehot], -1)
        xh = jnp.concatenate([x, hstate], -1)
        r = jax.nn.sigmoid(xh @ p["gru_r"]["w"] + p["gru_r"]["b"])
        u = jax.nn.sigmoid(xh @ p["gru_u"]["w"] + p["gru_u"]["b"])
        xr = jnp.concatenate([x, r * hstate], -1)
        c = jnp.tanh(xr @ p["gru_c"]["w"] + p["gru_c"]["b"])
        return u * hstate + (1.0 - u) * c

    def _logits(self, raw):
        """[..., K*C] -> unimix'd log-probs [..., K, C]."""
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        lg = raw.reshape(*raw.shape[:-1], cfg.stoch_groups, cfg.stoch_classes)
        probs = jax.nn.softmax(lg, -1)
        probs = (1 - cfg.unimix) * probs + cfg.unimix / cfg.stoch_classes
        return jnp.log(probs)

    def sample_z(self, rng, logp):
        """Straight-through one-hot sample from [..., K, C] log-probs -> [..., K*C]."""
        import jax
        import jax.numpy as jnp

        idx = jax.random.categorical(rng, logp, axis=-1)
        onehot = jax.nn.one_hot(idx, self.cfg.stoch_classes, dtype=logp.dtype)
        probs = jnp.exp(logp)
        st = onehot + probs - jax.lax.stop_gradient(probs)
        return st.reshape(*st.shape[:-2], self.z_size)

    def kl(self, logp_a, logp_b):
        """KL(a || b) over [..., K, C] log-probs, summed over groups."""
        import jax.numpy as jnp

        return (jnp.exp(logp_a) * (logp_a - logp_b)).sum(-1).sum(-1)

    # -- heads ------------------------------------------------------------
    def feat(self, hstate, z):
        import jax.numpy as jnp

        return jnp.concatenate([hstate, z], -1)

    def decode(self, p, f):
        return _mlp(p["dec"], f)

    def reward(self, p, f):
        return _mlp(p["rew"], f)[..., 0]  # symlog space

    def cont(self, p, f):
        return _mlp(p["cont"], f)[..., 0]  # logit

    def actor_logits(self, p, f):
        return _mlp(p["actor"], f)

    def value(self, p, f):
        return _mlp(p["critic"], f)[..., 0]  # symlog space


# ------------------------------------------------------------------- replay

class _StreamBuffer:
    """Contiguous STATE stream with is_first markers (reference: Dreamer's
    episodic replay sampled as fixed-length windows).

    Row t holds: obs_t, the action taken AT t, the reward received ENTERING t,
    and whether t is terminal. Terminal observations get their own row (with a
    dummy action that the next row's is_first masking neutralizes) — without
    them the continue head would never see a cont=0 target and imagination
    would never terminate."""

    def __init__(self, capacity: int, obs_dim: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros((capacity,), np.int64)
        self.rew_in = np.zeros((capacity,), np.float32)
        self.terms = np.zeros((capacity,), np.float32)
        self.is_first = np.zeros((capacity,), np.float32)
        self.ptr = 0
        self.full = False

    def __len__(self):
        return self.capacity if self.full else self.ptr

    def _push(self, obs, action, rew_in, term, first) -> None:
        i = self.ptr
        self.obs[i] = obs
        self.actions[i] = action
        self.rew_in[i] = rew_in
        self.terms[i] = term
        self.is_first[i] = first
        self.ptr = (self.ptr + 1) % self.capacity
        if self.ptr == 0:
            self.full = True

    def add_episodes(self, episodes: List[Dict[str, np.ndarray]]) -> int:
        added = 0
        for ep in episodes:
            n = len(ep["actions"])
            for t in range(n):
                self._push(ep["obs"][t], ep["actions"][t],
                           ep["rewards"][t - 1] if t > 0 else 0.0,
                           0.0, 1.0 if t == 0 else 0.0)
                added += 1
            # ALWAYS write the final-state row (its dummy action is masked by
            # the next row's is_first): it carries the episode's LAST reward,
            # which would otherwise be censored for truncated/chunked episodes,
            # and the cont=0 target when the episode truly terminated
            self._push(ep["next_obs_last"], 0, ep["rewards"][n - 1],
                       1.0 if ep["terminated"] else 0.0, 0.0)
            added += 1
        return added

    def sample(self, batch: int, length: int, rng: np.random.Generator) -> Dict[str, np.ndarray]:
        hi = len(self) - length
        starts = rng.integers(0, max(1, hi + 1), size=batch)
        # logical index 0 = OLDEST row (= ptr once the ring wrapped): windows
        # over logical positions are always time-contiguous, never splicing the
        # newest data onto the oldest across the write pointer
        base = self.ptr if self.full else 0
        idx = (base + starts[:, None] + np.arange(length)[None, :]) % self.capacity
        out = {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rew_in": self.rew_in[idx],
            "terms": self.terms[idx],
            "is_first": self.is_first[idx],  # fancy indexing already copies
        }
        out["is_first"][:, 0] = 1.0  # window start = state reset (no context)
        out["rew_in"][:, 0] = 0.0  # fresh context: no entering reward
        return out


# ------------------------------------------------------------------- learner

class DreamerV3Learner(Learner):
    """World model + actor + critic, each with its own optimizer; both phases
    are single jitted programs (reference dreamerv3 torch_learner)."""

    def build(self) -> None:
        import jax
        import optax

        cfg = self.config
        obs_dim = int(np.prod(self.module.observation_space.shape))
        n_actions = int(self.module.action_space.n)
        self.nets = _DreamerNets(obs_dim, n_actions, cfg)
        self.params = self.nets.init_params(seed=cfg.seed or 0)
        self.params = jax.tree_util.tree_map(np.asarray, self.params)
        self.critic_ema = jax.tree_util.tree_map(np.array, self.params["critic"])

        def chain(lr):
            return optax.chain(optax.clip_by_global_norm(cfg.grad_clip or 100.0),
                               optax.adam(lr))

        self._wm_keys = ("enc", "gru_r", "gru_u", "gru_c", "prior", "post",
                         "dec", "rew", "cont")
        self.opt_world = chain(cfg.lr_world)
        self.opt_actor = chain(cfg.lr_actor)
        self.opt_critic = chain(cfg.lr_critic)
        self.st_world = self.opt_world.init({k: self.params[k] for k in self._wm_keys})
        self.st_actor = self.opt_actor.init(self.params["actor"])
        self.st_critic = self.opt_critic.init(self.params["critic"])
        # EMA of the 5th..95th percentile return range (actor normalization)
        self.ret_range = 1.0
        self._rng = jax.random.PRNGKey((self.config.seed or 0) + 7)
        self._wm_fn = self._build_wm_fn()
        self._ac_fn = self._build_ac_fn()
        self.metrics: Dict[str, Any] = {}

    # -- world model phase ------------------------------------------------
    def _build_wm_fn(self):
        import jax
        import jax.numpy as jnp

        nets, cfg = self.nets, self.config

        def wm_loss(params, batch, rng):
            b, length = batch["actions"].shape
            obs = _symlog(batch["obs"])
            embed = _mlp(params["enc"], obs)  # [B, L, E]
            a_onehot = jax.nn.one_hot(batch["actions"], nets.n_actions, dtype=obs.dtype)
            # prev action for the sequence model, zeroed where an episode starts
            keep = (1.0 - batch["is_first"])[..., None]
            prev_a = jnp.roll(a_onehot, 1, axis=1) * keep
            # per-state targets stored directly in the stream: reward entering
            # the state, and whether the state is terminal (cont = 1 - term)
            tgt_r = batch["rew_in"]
            tgt_cont = 1.0 - batch["terms"]

            h0 = jnp.zeros((b, cfg.deter_size), obs.dtype)
            z0 = jnp.zeros((b, nets.z_size), obs.dtype)
            keys = jax.random.split(rng, length)

            def step(carry, xs):
                hstate, z = carry
                emb_t, a_t, first_t, key = xs
                mask = (1.0 - first_t)[:, None]
                hstate = hstate * mask
                z = z * mask
                hstate = nets.gru(params, hstate, z, a_t * mask)
                post_lp = nets._logits(_mlp(params["post"],
                                            jnp.concatenate([hstate, emb_t], -1)))
                prior_lp = nets._logits(_mlp(params["prior"], hstate))
                z = nets.sample_z(key, post_lp)
                return (hstate, z), (hstate, z, post_lp, prior_lp)

            (_, _), (hs, zs, post_lp, prior_lp) = jax.lax.scan(
                step, (h0, z0),
                (embed.transpose(1, 0, 2), prev_a.transpose(1, 0, 2),
                 batch["is_first"].T, keys))
            hs = hs.transpose(1, 0, 2)  # [B, L, D]
            zs = zs.transpose(1, 0, 2)
            post_lp = post_lp.transpose(1, 0, 2, 3)
            prior_lp = prior_lp.transpose(1, 0, 2, 3)
            f = nets.feat(hs, zs)

            recon = nets.decode(params, f)
            loss_rec = ((recon - obs) ** 2).sum(-1).mean()
            loss_rew = ((nets.reward(params, f) - _symlog(tgt_r)) ** 2).mean()
            cont_logit = nets.cont(params, f)
            loss_cont = jnp.mean(
                jnp.maximum(cont_logit, 0) - cont_logit * tgt_cont
                + jnp.log1p(jnp.exp(-jnp.abs(cont_logit))))
            sg = jax.lax.stop_gradient
            kl_dyn = jnp.maximum(cfg.free_bits,
                                 nets.kl(sg(post_lp), prior_lp)).mean()
            kl_rep = jnp.maximum(cfg.free_bits,
                                 nets.kl(post_lp, sg(prior_lp))).mean()
            loss = (cfg.beta_pred * (loss_rec + loss_rew + loss_cont)
                    + cfg.beta_dyn * kl_dyn + cfg.beta_rep * kl_rep)
            aux = {"wm_loss": loss, "recon_loss": loss_rec, "reward_loss": loss_rew,
                   "cont_loss": loss_cont, "kl_dyn": kl_dyn, "kl_rep": kl_rep,
                   "starts_h": sg(hs.reshape(-1, cfg.deter_size)),
                   "starts_z": sg(zs.reshape(-1, nets.z_size))}
            return loss, aux

        grad_fn = jax.value_and_grad(
            lambda wm, rest, batch, rng: wm_loss({**wm, **rest}, batch, rng),
            has_aux=True)

        @jax.jit
        def update(params, batch, rng):
            wm = {k: params[k] for k in self._wm_keys}
            rest = {k: params[k] for k in params if k not in self._wm_keys}
            (loss, aux), grads = grad_fn(wm, rest, batch, rng)
            return loss, aux, grads

        return update

    # -- imagination + actor-critic phase ---------------------------------
    def _build_ac_fn(self):
        import jax
        import jax.numpy as jnp

        nets, cfg = self.nets, self.config
        sg = jax.lax.stop_gradient

        def imagine(params, actor_p, h0, z0, rng):
            def step(carry, key):
                hstate, z = carry
                f = nets.feat(hstate, z)
                alogits = _mlp(actor_p, f)
                a = jax.random.categorical(key, alogits, axis=-1)
                a1 = jax.nn.one_hot(a, nets.n_actions, dtype=f.dtype)
                h2 = nets.gru(params, hstate, z, a1)
                z2 = nets.sample_z(jax.random.fold_in(key, 1),
                                   nets._logits(_mlp(params["prior"], h2)))
                return (h2, z2), (hstate, z, a, h2, z2)

            keys = jax.random.split(rng, cfg.imag_horizon)
            _, (hs, zs, acts, h2s, z2s) = jax.lax.scan(step, (h0, z0), keys)
            return hs, zs, acts, h2s, z2s  # [H, N, ...]

        def losses(actor_p, critic_p, params, critic_ema, h0, z0, rng, ret_range):
            hs, zs, acts, h2s, z2s = imagine(params, actor_p, h0, z0, rng)
            f_next = nets.feat(h2s, z2s)  # state entered by each imagined action
            rew = _symexp(nets.reward(params, f_next))  # [H, N]
            cont = jax.nn.sigmoid(nets.cont(params, f_next))
            v_next = _symexp(nets.value({"critic": critic_p}, f_next))
            # lambda-returns backwards over the horizon
            def lam_step(nxt, xs):
                r, c, v = xs
                ret = r + cfg.gamma * c * ((1 - cfg.lambda_) * v + cfg.lambda_ * nxt)
                return ret, ret

            last = v_next[-1]
            _, rets = jax.lax.scan(lam_step, last, (rew, cont, v_next), reverse=True)
            rets = sg(rets)  # [H, N]
            f_cur = nets.feat(hs, zs)
            # discounted trajectory weights (stop after predicted termination)
            w = sg(jnp.cumprod(jnp.concatenate(
                [jnp.ones_like(cont[:1]), cfg.gamma * cont[:-1]], 0), 0))
            # actor: REINFORCE with normalized advantage + entropy
            alogits = _mlp(actor_p, f_cur)
            logp_all = jax.nn.log_softmax(alogits)
            logp_a = jnp.take_along_axis(logp_all, acts[..., None], -1)[..., 0]
            v_cur = sg(_symexp(nets.value({"critic": critic_p}, f_cur)))
            adv = (rets - v_cur) / jnp.maximum(1.0, ret_range)
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1)
            actor_loss = -(w * (logp_a * sg(adv) + cfg.entropy_coef * entropy)).mean()
            # critic: symlog regression to lambda-returns + EMA regularizer
            v_pred = nets.value({"critic": critic_p}, sg(f_cur))
            v_ema = sg(nets.value({"critic": critic_ema}, sg(f_cur)))
            critic_loss = (w * ((v_pred - _symlog(rets)) ** 2
                                + 0.3 * (v_pred - v_ema) ** 2)).mean()
            aux = {"actor_loss": actor_loss, "critic_loss": critic_loss,
                   "imag_return": rets.mean(), "actor_entropy": entropy.mean(),
                   "ret_p95": jnp.percentile(rets, 95),
                   "ret_p5": jnp.percentile(rets, 5)}
            return actor_loss + critic_loss, aux

        grad_fn = jax.value_and_grad(losses, argnums=(0, 1), has_aux=True)

        @jax.jit
        def update(params, critic_ema, h0, z0, rng, ret_range):
            (loss, aux), (g_actor, g_critic) = grad_fn(
                params["actor"], params["critic"], params, critic_ema,
                h0, z0, rng, ret_range)
            return loss, aux, g_actor, g_critic

        return update

    # -- the composite update ---------------------------------------------
    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        import jax
        import optax

        cfg = self.config
        self._rng, k1, k2 = jax.random.split(self._rng, 3)
        _, aux, wm_grads = self._wm_fn(self.params, batch, k1)
        wm_grads = self._sync_grads(wm_grads)
        wm_params = {k: self.params[k] for k in self._wm_keys}
        upd, self.st_world = self.opt_world.update(wm_grads, self.st_world, wm_params)
        wm_params = optax.apply_updates(wm_params, upd)
        self.params.update(jax.tree_util.tree_map(np.asarray, wm_params))

        h0, z0 = aux.pop("starts_h"), aux.pop("starts_z")
        _, ac_aux, g_actor, g_critic = self._ac_fn(
            self.params, self.critic_ema, h0, z0, k2, float(self.ret_range))
        g_actor = self._sync_grads(g_actor)
        g_critic = self._sync_grads(g_critic)
        upd_a, self.st_actor = self.opt_actor.update(
            g_actor, self.st_actor, self.params["actor"])
        self.params["actor"] = jax.tree_util.tree_map(
            np.asarray, optax.apply_updates(self.params["actor"], upd_a))
        upd_c, self.st_critic = self.opt_critic.update(
            g_critic, self.st_critic, self.params["critic"])
        self.params["critic"] = jax.tree_util.tree_map(
            np.asarray, optax.apply_updates(self.params["critic"], upd_c))
        d = cfg.critic_ema_decay
        self.critic_ema = jax.tree_util.tree_map(
            lambda e, p: np.asarray(d * e + (1 - d) * p),
            self.critic_ema, self.params["critic"])
        rng_now = float(ac_aux.pop("ret_p95")) - float(ac_aux.pop("ret_p5"))
        self.ret_range = (cfg.retnorm_decay * self.ret_range
                          + (1 - cfg.retnorm_decay) * rng_now)
        self.metrics = {k: float(v) for k, v in {**aux, **ac_aux}.items()}
        self.metrics["total_loss"] = self.metrics["wm_loss"]
        self.metrics["ret_range"] = float(self.ret_range)
        return self.metrics

    def get_weights(self):
        return self.params

    def get_state(self) -> Dict[str, Any]:
        return {"params": self.params, "critic_ema": self.critic_ema,
                "st_world": self.st_world, "st_actor": self.st_actor,
                "st_critic": self.st_critic, "ret_range": self.ret_range}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.critic_ema = state.get("critic_ema", self.critic_ema)
        for k in ("st_world", "st_actor", "st_critic"):
            if state.get(k) is not None:
                setattr(self, k, state[k])
        self.ret_range = state.get("ret_range", self.ret_range)


# ------------------------------------------------------------------- env runner

class DreamerV3EnvRunner:
    """Recurrent rollout actor: carries (h, z, prev_action) per env and resets
    them on episode boundaries (reference dreamerv3/utils/env_runner.py)."""

    def __init__(self, config: DreamerV3Config, worker_index: int = 0):
        import gymnasium as gym
        import jax

        self.config = config
        self.worker_index = worker_index
        self.num_envs = config.num_envs_per_env_runner
        maker = config.env_maker()
        self.env = gym.vector.SyncVectorEnv([maker for _ in range(self.num_envs)])
        single = maker()
        obs_dim = int(np.prod(single.observation_space.shape))
        self.nets = _DreamerNets(obs_dim, int(single.action_space.n), config)
        single.close()
        self.params = self.nets.init_params(seed=config.seed or 0)
        self._jrng = jax.random.PRNGKey((config.seed or 0) + 100 + worker_index)
        self.rng = np.random.default_rng((config.seed or 0) + worker_index + 1)
        self._obs = None
        self.metrics: Dict[str, Any] = {}
        self._act = self._build_act_fn()

    def _build_act_fn(self):
        import jax
        import jax.numpy as jnp

        nets = self.nets

        @jax.jit
        def act(params, hstate, z, prev_a, obs, first, rng, explore):
            mask = (1.0 - first)[:, None]
            hstate = hstate * mask
            z = z * mask
            prev_a = prev_a * mask
            hstate = nets.gru(params, hstate, z, prev_a)
            embed = _mlp(params["enc"], _symlog(obs))
            post_lp = nets._logits(_mlp(params["post"],
                                        jnp.concatenate([hstate, embed], -1)))
            k1, k2 = jax.random.split(rng)
            z = nets.sample_z(k1, post_lp)
            logits = nets.actor_logits(params, nets.feat(hstate, z))
            a = jnp.where(explore,
                          jax.random.categorical(k2, logits, axis=-1),
                          jnp.argmax(logits, axis=-1))
            return hstate, z, a

        return act

    # -- weights / control --------------------------------------------------
    def get_state(self):
        return {"params": self.params}

    def set_state(self, state):
        self.params = state["params"]

    def set_weights(self, params):
        self.params = params

    def get_metrics(self):
        return self.metrics

    def ping(self) -> bool:
        return True

    def stop(self) -> None:
        try:
            self.env.close()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass

    # -- sampling -----------------------------------------------------------
    def _reset_if_needed(self):
        from ..env.episode import SingleAgentEpisode

        if self._obs is None:
            obs, _ = self.env.reset(seed=(self.config.seed or 0) + self.worker_index)
            self._obs = obs
            n = self.num_envs
            self._episodes = [SingleAgentEpisode() for _ in range(n)]
            for i in range(n):
                self._episodes[i].add_env_reset(obs[i])
            self._prev_done = np.zeros(n, dtype=bool)
            self._first = np.ones(n, np.float32)
            self._h = np.zeros((n, self.config.deter_size), np.float32)
            self._z = np.zeros((n, self.nets.z_size), np.float32)
            self._pa = np.zeros((n, self.nets.n_actions), np.float32)

    def sample(self, num_timesteps: Optional[int] = None, explore: bool = True):
        import jax

        from ..env.episode import SingleAgentEpisode

        cfg = self.config
        num_timesteps = num_timesteps or cfg.rollout_fragment_length * self.num_envs
        self._reset_if_needed()
        done_eps = []
        returns: List[float] = []
        steps = 0
        while steps < num_timesteps:
            self._jrng, key = jax.random.split(self._jrng)
            h2, z2, a = self._act(self.params, self._h, self._z, self._pa,
                                  np.asarray(self._obs, np.float32),
                                  self._first, key, explore)
            self._h, self._z = np.asarray(h2), np.asarray(z2)
            actions = np.asarray(a)
            self._pa = np.eye(self.nets.n_actions, dtype=np.float32)[actions]
            self._first = np.zeros(self.num_envs, np.float32)
            obs, rewards, terms, truncs, _ = self.env.step(actions)
            for i in range(self.num_envs):
                if self._prev_done[i]:
                    self._episodes[i] = SingleAgentEpisode()
                    self._episodes[i].add_env_reset(obs[i])
                    self._prev_done[i] = False
                    self._first[i] = 1.0
                    continue
                ep = self._episodes[i]
                ep.add_env_step(obs[i], actions[i], rewards[i], terms[i], truncs[i])
                steps += 1
                if terms[i] or truncs[i]:
                    returns.append(ep.get_return())
                    done_eps.append(ep)
                    self._prev_done[i] = True
                    self._first[i] = 1.0
            self._obs = obs
        for i in range(self.num_envs):
            if not self._prev_done[i] and len(self._episodes[i]):
                done_eps.append(self._episodes[i])
                self._episodes[i] = SingleAgentEpisode()
                self._episodes[i].add_env_reset(self._obs[i])
        self.metrics = {
            "num_env_steps_sampled": steps,
            "episode_return_mean": float(np.mean(returns)) if returns else None,
            "num_episodes": len(returns),
        }
        return [ep.to_numpy() for ep in done_eps]


# ------------------------------------------------------------------- algorithm

class DreamerV3(Algorithm):
    learner_class = DreamerV3Learner
    env_runner_cls = DreamerV3EnvRunner  # recurrent rollout actors

    @classmethod
    def get_default_config(cls) -> DreamerV3Config:
        return DreamerV3Config(cls)

    def setup(self, _config) -> None:
        super().setup(_config)
        cfg = self._algo_config
        obs_dim = int(np.prod(self.module_spec.observation_space.shape))
        self.buffer = _StreamBuffer(cfg.replay_capacity, obs_dim)
        self._np_rng = np.random.default_rng(cfg.seed or 0)
        self._env_steps = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self._algo_config
        episodes = self.env_runner_group.sample(cfg.sample_timesteps_per_iteration)
        self.buffer.add_episodes(episodes)
        self._env_steps += sum(len(ep["actions"]) for ep in episodes)
        for m in self.env_runner_group.get_metrics():
            self.metrics.log_dict({k: v for k, v in m.items() if v is not None},
                                  window=20)
        warm = (len(self.buffer)
                >= max(cfg.num_steps_sampled_before_learning_starts,
                       cfg.seq_len * 2))
        if warm:
            for _ in range(cfg.num_updates_per_iteration):
                batch = self.buffer.sample(cfg.batch_size_seqs, cfg.seq_len,
                                           self._np_rng)
                for lm in self.learner_group.update(batch):
                    self.metrics.log_dict(lm)
            self.env_runner_group.sync_weights(self.learner_group.get_weights())
        result = self.metrics.reduce()
        result["num_env_steps_sampled_lifetime"] = self._env_steps
        return result
