"""AlgorithmConfig: fluent builder.

Capability parity: reference rllib/algorithms/algorithm_config.py (6,259 LoC fluent
builder) — .environment()/.training()/.env_runners()/.learners()/.framework() chaining,
build_algo(). Only the knobs the TPU build uses are carried.
"""
from __future__ import annotations

import copy
import os
from typing import Any, Callable, Dict, Optional


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[type] = None):
        self.algo_class = algo_class
        # environment
        self.env: Any = None
        self.env_config: Dict[str, Any] = {}
        # env runners
        self.num_env_runners: int = 2
        self.num_envs_per_env_runner: int = 4
        self.rollout_fragment_length: int = 64
        # training
        self.lr: float = 3e-4
        self.gamma: float = 0.99
        self.train_batch_size: int = 2048
        self.minibatch_size: int = 256
        self.num_epochs: int = 8
        self.grad_clip: Optional[float] = None
        # learners
        self.num_learners: int = 1
        self.num_tpus_per_learner: float = 0
        # opt-in int8 wire compression for the learners' host-plane collective
        # (grad allreduce rides the data-plane ring; see util/collective)
        self.collective_compression: Optional[str] = None
        # module
        self.model_config: Dict[str, Any] = {}
        self.rl_module_class: Optional[type] = None
        # offline (reference offline_data.py)
        self.input_: Any = None  # parquet/json path(s)
        self.input_dataset: Any = None  # pre-built ray_tpu.data Dataset
        self.observation_space: Any = None  # offline mode: spaces given, no env probe
        self.action_space: Any = None
        # multi-agent (reference AlgorithmConfig.multi_agent)
        self.policies: Optional[Dict[str, Any]] = None  # mid -> (obs_space, act_space) | None
        self.policy_mapping_fn: Callable[[Any], str] = lambda agent_id: "default_policy"
        self.base_learner_class: Optional[type] = None  # per-module learner inside MultiAgentLearner
        # decoupled rollout/learn plane (rllib/rollout_plane.py): env-var
        # defaults are the registered RAY_TPU_RL_* knobs
        self.decoupled: bool = False
        self.decoupled_block_T: Optional[int] = None  # None = rollout_fragment_length
        self.decoupled_queue_depth: int = int(
            os.environ.get("RAY_TPU_RL_QUEUE_DEPTH", "8"))
        self.max_block_lag: int = int(
            os.environ.get("RAY_TPU_RL_MAX_BLOCK_LAG", "4"))
        self.correction: str = os.environ.get("RAY_TPU_RL_CORRECTION", "is_clip")
        self.weight_sync_interval: int = int(
            os.environ.get("RAY_TPU_RL_WEIGHT_SYNC_INTERVAL", "1"))
        self.blocks_per_update: int = int(
            os.environ.get("RAY_TPU_RL_BLOCKS_PER_UPDATE", "1"))
        self.take_timeout_s: float = float(
            os.environ.get("RAY_TPU_RL_TAKE_TIMEOUT_S", "30"))
        self.producer_slack: int = int(
            os.environ.get("RAY_TPU_RL_PRODUCER_SLACK", "2"))
        self.max_failures: int = 1  # learner restarts from checkpoint before giving up
        # misc
        self.seed: Optional[int] = 0
        self.explore: bool = True

    # -- fluent sections (reference algorithm_config.py) -----------------------
    def environment(self, env=None, *, env_config: Optional[Dict] = None,
                    observation_space=None, action_space=None) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        if observation_space is not None:
            self.observation_space = observation_space
        if action_space is not None:
            self.action_space = action_space
        return self

    def offline_data(self, *, input_=None, dataset=None, **_compat) -> "AlgorithmConfig":
        """Offline-RL input (reference AlgorithmConfig.offline_data / offline_data.py:30)."""
        if input_ is not None:
            self.input_ = input_
        if dataset is not None:
            self.input_dataset = dataset
        return self

    def env_runners(
        self,
        *,
        num_env_runners: Optional[int] = None,
        num_envs_per_env_runner: Optional[int] = None,
        rollout_fragment_length: Optional[int] = None,
        **_compat,
    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(
        self,
        *,
        lr: Optional[float] = None,
        gamma: Optional[float] = None,
        train_batch_size: Optional[int] = None,
        minibatch_size: Optional[int] = None,
        num_epochs: Optional[int] = None,
        grad_clip: Optional[float] = None,
        **kwargs,
    ) -> "AlgorithmConfig":
        for k, v in dict(
            lr=lr, gamma=gamma, train_batch_size=train_batch_size,
            minibatch_size=minibatch_size, num_epochs=num_epochs, grad_clip=grad_clip,
        ).items():
            if v is not None:
                setattr(self, k, v)
        for k, v in kwargs.items():
            if hasattr(self, k) and v is not None:
                setattr(self, k, v)
        return self

    def learners(
        self, *, num_learners: Optional[int] = None, num_tpus_per_learner: Optional[float] = None,
        collective_compression: Optional[str] = None, **_compat
    ) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        if num_tpus_per_learner is not None:
            self.num_tpus_per_learner = num_tpus_per_learner
        if collective_compression is not None:
            self.collective_compression = collective_compression
        return self

    def rl_module(self, *, model_config: Optional[Dict] = None, rl_module_class: Optional[type] = None) -> "AlgorithmConfig":
        if model_config is not None:
            self.model_config = dict(model_config)
        if rl_module_class is not None:
            self.rl_module_class = rl_module_class
        return self

    def multi_agent(self, *, policies=None, policy_mapping_fn=None, **_compat) -> "AlgorithmConfig":
        """Declare policy modules + agent->module mapping (reference .multi_agent())."""
        if policies is not None:
            # accept {mid: None} or {mid: (obs_space, act_space)} or a list/set of mids
            if isinstance(policies, (list, tuple, set)):
                policies = {mid: None for mid in policies}
            self.policies = dict(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    @property
    def is_multi_agent(self) -> bool:
        return self.policies is not None

    def resolved_policy_specs(self, env) -> Dict[str, "RLModuleSpec"]:  # noqa: F821
        """Per-module RLModuleSpecs with spaces from config or probed from the env."""
        from ..core.rl_module import RLModuleSpec

        specs = {}
        for mid, spaces in (self.policies or {"default_policy": None}).items():
            if spaces is not None:
                obs_space, act_space = spaces
            else:
                # probe: spaces of the first agent mapped to this module
                aid = next((a for a in env.possible_agents if self.policy_mapping_fn(a) == mid),
                           env.possible_agents[0])
                obs_space = env.observation_space_for(aid)
                act_space = env.action_space_for(aid)
            specs[mid] = RLModuleSpec(
                module_class=self.rl_module_class,
                observation_space=obs_space,
                action_space=act_space,
                model_config=self.model_config,
            )
        return specs

    def decoupled_rollout(
        self,
        *,
        enabled: bool = True,
        block_T: Optional[int] = None,
        queue_depth: Optional[int] = None,
        max_block_lag: Optional[int] = None,
        correction: Optional[str] = None,
        weight_sync_interval: Optional[int] = None,
        blocks_per_update: Optional[int] = None,
        take_timeout_s: Optional[float] = None,
        max_failures: Optional[int] = None,
        producer_slack: Optional[int] = None,
    ) -> "AlgorithmConfig":
        """Opt into the decoupled actor–learner rollout plane.

        `correction` picks the off-policy correction applied to stale blocks:
        "is_clip" (PPO ratio clipping over behaviour-policy GAE, the default)
        or "vtrace" (current-policy values + V-trace targets, IMPALA-style).
        `producer_slack` is the queue depth beyond which workers pace
        themselves instead of sampling blocks destined for eviction (<= 0
        disables pacing; workers then free-run).
        """
        self.decoupled = bool(enabled)
        if correction is not None and correction not in ("is_clip", "vtrace"):
            raise ValueError(
                f"correction must be 'is_clip' or 'vtrace', got {correction!r}")
        for k, v in dict(
            decoupled_block_T=block_T, decoupled_queue_depth=queue_depth,
            max_block_lag=max_block_lag, correction=correction,
            weight_sync_interval=weight_sync_interval,
            blocks_per_update=blocks_per_update,
            take_timeout_s=take_timeout_s, max_failures=max_failures,
            producer_slack=producer_slack,
        ).items():
            if v is not None:
                setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def framework(self, *_a, **_k) -> "AlgorithmConfig":
        return self  # jax-only build

    # -- env factory -----------------------------------------------------------
    def env_maker(self) -> Callable[[], Any]:
        env, env_config = self.env, dict(self.env_config)
        if callable(env):
            return lambda: env(env_config)

        def make():
            import gymnasium as gym

            return gym.make(env, **env_config)

        return make

    def copy(self) -> "AlgorithmConfig":
        # share the (possibly large, materialized) offline dataset by reference
        ds, self.input_dataset = self.input_dataset, None
        try:
            new = copy.deepcopy(self)
        finally:
            self.input_dataset = ds
        new.input_dataset = ds
        return new

    def build_algo(self) -> "Algorithm":  # noqa: F821
        if self.algo_class is None:
            raise ValueError("no algo_class bound to this config")
        return self.algo_class(self.copy())

    build = build_algo  # older reference API name
