"""Algorithm: the top-level control loop, usable standalone or under Tune.

Capability parity: reference rllib/algorithms/algorithm.py — is a Tune Trainable;
train() -> training_step(); checkpointing via get/set_state (Checkpointable tree:
Algorithm -> LearnerGroup -> Learner -> RLModule params).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu import tune

from ..core.learner import Learner
from ..core.learner_group import LearnerGroup
from ..core.rl_module import RLModuleSpec
from ..env.env_runner_group import EnvRunnerGroup
from ..utils.metrics_logger import MetricsLogger
from .algorithm_config import AlgorithmConfig


class Algorithm(tune.Trainable):
    learner_class: type = Learner
    env_runner_cls = None  # custom rollout actor class (None = SingleAgentEnvRunner)

    def __init__(self, config):
        if isinstance(config, dict):  # Tune passes plain dicts
            base = self.get_default_config()
            for k, v in config.items():
                setattr(base, k, v)
            config = base
        self._algo_config = config
        super().__init__({})

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return AlgorithmConfig(cls)

    # -- Trainable hooks -------------------------------------------------------
    def setup(self, _config: Dict[str, Any]) -> None:
        from ray_tpu.usage import record_library_usage

        record_library_usage("rllib")
        cfg = self._algo_config
        self.metrics = MetricsLogger()
        self.rollout_plane = None
        self._policy_version = 0
        self._updates_total = 0
        self._updates_since_sync = 0
        self._ckpt_interval = 10
        self._learner_failures = 0
        self._last_failure: Optional[BaseException] = None
        self._last_ckpt = None
        if cfg.env is not None:
            if getattr(cfg, "decoupled", False):
                # decoupled mode replaces the RPC-sampling group with the
                # rollout plane (built after the learner group below)
                self.env_runner_group = None
            else:
                # subclasses with custom rollout actors (e.g. DreamerV3's
                # recurrent runner) override env_runner_cls instead of
                # rebuilding the group
                self.env_runner_group = EnvRunnerGroup(cfg, runner_cls=self.env_runner_cls)
            probe = cfg.env_maker()()
            obs_space, act_space = probe.observation_space, probe.action_space
            probe.close()
        else:
            # offline mode (reference offline algos): spaces come from the config
            self.env_runner_group = None
            obs_space, act_space = cfg.observation_space, cfg.action_space
            if obs_space is None or act_space is None:
                raise ValueError(
                    "offline algorithms need .environment(observation_space=..., action_space=...)"
                )
        self.module_spec = RLModuleSpec(
            module_class=cfg.rl_module_class,
            observation_space=obs_space,
            action_space=act_space,
            model_config=cfg.model_config,
        )
        self.learner_group = LearnerGroup(cfg, self.module_spec, self.learner_class)
        # host-side module copy for connectors (GAE bootstrap values)
        self._module = self.module_spec.build()
        if self.env_runner_group is not None:
            self.env_runner_group.sync_weights(self.learner_group.get_weights())
        if getattr(cfg, "decoupled", False) and cfg.env is not None:
            import os

            from ..rollout_plane import RolloutPlane

            # workers derive version-0 params from the same seeded module
            # init as the learners, so no initial broadcast is needed
            self._plane_authkey = os.urandom(16)
            self.learner_group.setup_decoupled(self._plane_authkey)
            self.rollout_plane = RolloutPlane(cfg, authkey=self._plane_authkey)
            self._last_ckpt = self.learner_group.get_state()

    def step(self) -> Dict[str, Any]:
        return self.training_step()

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # -- decoupled rollout/learn plane -----------------------------------------
    def _decoupled_training_step(self) -> Dict[str, Any]:
        """One learner-paced step against the rollout plane: take a batch of
        trajectory-block handles (staleness-filtered by the queue), update the
        learner group, release the blocks, broadcast fresh weights. Learner
        death restarts the group from the last checkpoint (max_failures)."""
        from ray_tpu.core.exceptions import (ActorError, CollectiveAbortError,
                                             WorkerCrashedError)

        cfg = self._algo_config
        n = max(1, cfg.num_learners)
        want = max(1, cfg.blocks_per_update)
        want += (-want) % n  # each learner must see the same block count
        handles = self.rollout_plane.take(
            want, self._policy_version, timeout_s=cfg.take_timeout_s)
        if n > 1 and len(handles) % n:
            extra = handles[-(len(handles) % n):]
            handles = handles[: len(handles) - len(extra)]
            self.rollout_plane.release(extra)
        if not handles:
            return self.metrics.reduce()
        try:
            results = self.learner_group.update_from_blocks(handles)
        except (CollectiveAbortError, ActorError, WorkerCrashedError,
                ConnectionError) as e:
            self.rollout_plane.release(handles)
            self._restore_learners(e)
            return self.metrics.reduce()
        self.rollout_plane.release(handles)
        self._updates_total += 1
        self._updates_since_sync += 1
        if self._updates_since_sync >= max(1, cfg.weight_sync_interval):
            version, addr, nbytes = self.learner_group.publish_weights()
            self._policy_version = version
            self.rollout_plane.set_weights(version, addr, nbytes)
            self._updates_since_sync = 0
        if self._updates_total % self._ckpt_interval == 0:
            self._last_ckpt = self.learner_group.get_state()
        for lm in results:
            self.metrics.log_dict(lm)
        if self._updates_total % 5 == 0:
            for m in self.rollout_plane.worker_metrics():
                self.metrics.log_dict(
                    {k: v for k, v in m.items() if v is not None}, window=20)
        result = self.metrics.reduce()
        result["num_env_steps_trained"] = int(
            sum(h.env_steps for h in handles))
        result["policy_version"] = self._policy_version
        result["learner_failures"] = self._learner_failures
        return result

    def _restore_learners(self, exc: BaseException) -> None:
        """Learner-rank death: tear the group down and restart it from the
        last checkpoint, re-attaching it to the rollout plane with version
        continuity so workers keep accepting newer broadcasts."""
        cfg = self._algo_config
        self._learner_failures += 1
        self._last_failure = exc
        if self._learner_failures > getattr(cfg, "max_failures", 1):
            raise exc
        try:
            self.learner_group.shutdown()
        # graftlint: allow[swallowed-exception] group is already (partially) dead — that is the trigger
        except Exception:
            pass
        self.learner_group = LearnerGroup(cfg, self.module_spec, self.learner_class)
        if self._last_ckpt is not None:
            self.learner_group.set_state(self._last_ckpt)
        if self.rollout_plane is not None:
            self.learner_group.setup_decoupled(
                self._plane_authkey, start_version=self._policy_version)
            version, addr, nbytes = self.learner_group.publish_weights()
            self._policy_version = version
            self.rollout_plane.set_weights(version, addr, nbytes)
            self._updates_since_sync = 0

    def save_checkpoint(self) -> Any:
        return {"learner": self.learner_group.get_state(), "config": None}

    def load_checkpoint(self, state: Any) -> None:
        self.learner_group.set_state(state["learner"])
        if self.env_runner_group is not None:
            self.env_runner_group.sync_weights(self.learner_group.get_weights())

    def cleanup(self) -> None:
        self.final_plane_stats: Optional[Dict[str, Any]] = None
        try:
            # getattr: subclasses with a custom setup() never build the plane
            if getattr(self, "rollout_plane", None) is not None:
                self.final_plane_stats = self.rollout_plane.shutdown()
                self.rollout_plane = None
            if self.env_runner_group is not None:
                self.env_runner_group.stop()
        finally:
            self.learner_group.shutdown()

    stop = cleanup  # reference Algorithm.stop()

    # -- convenience -----------------------------------------------------------
    def get_weights(self):
        return self.learner_group.get_weights()

    def evaluate(self, num_timesteps: int = 1000) -> Dict[str, Any]:
        if self.env_runner_group is None:
            return {"evaluation": {"episode_return_mean": None}}
        eps = self.env_runner_group.sample(num_timesteps, explore=False)
        rets = [float(e["rewards"].sum()) for e in eps if e["terminated"] or e["truncated"]]
        return {"evaluation": {"episode_return_mean": float(np.mean(rets)) if rets else None}}
