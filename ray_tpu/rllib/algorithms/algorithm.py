"""Algorithm: the top-level control loop, usable standalone or under Tune.

Capability parity: reference rllib/algorithms/algorithm.py — is a Tune Trainable;
train() -> training_step(); checkpointing via get/set_state (Checkpointable tree:
Algorithm -> LearnerGroup -> Learner -> RLModule params).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu import tune

from ..core.learner import Learner
from ..core.learner_group import LearnerGroup
from ..core.rl_module import RLModuleSpec
from ..env.env_runner_group import EnvRunnerGroup
from ..utils.metrics_logger import MetricsLogger
from .algorithm_config import AlgorithmConfig


class Algorithm(tune.Trainable):
    learner_class: type = Learner
    env_runner_cls = None  # custom rollout actor class (None = SingleAgentEnvRunner)

    def __init__(self, config):
        if isinstance(config, dict):  # Tune passes plain dicts
            base = self.get_default_config()
            for k, v in config.items():
                setattr(base, k, v)
            config = base
        self._algo_config = config
        super().__init__({})

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return AlgorithmConfig(cls)

    # -- Trainable hooks -------------------------------------------------------
    def setup(self, _config: Dict[str, Any]) -> None:
        from ray_tpu.usage import record_library_usage

        record_library_usage("rllib")
        cfg = self._algo_config
        self.metrics = MetricsLogger()
        if cfg.env is not None:
            # subclasses with custom rollout actors (e.g. DreamerV3's recurrent
            # runner) override env_runner_cls instead of rebuilding the group
            self.env_runner_group = EnvRunnerGroup(cfg, runner_cls=self.env_runner_cls)
            probe = cfg.env_maker()()
            obs_space, act_space = probe.observation_space, probe.action_space
            probe.close()
        else:
            # offline mode (reference offline algos): spaces come from the config
            self.env_runner_group = None
            obs_space, act_space = cfg.observation_space, cfg.action_space
            if obs_space is None or act_space is None:
                raise ValueError(
                    "offline algorithms need .environment(observation_space=..., action_space=...)"
                )
        self.module_spec = RLModuleSpec(
            module_class=cfg.rl_module_class,
            observation_space=obs_space,
            action_space=act_space,
            model_config=cfg.model_config,
        )
        self.learner_group = LearnerGroup(cfg, self.module_spec, self.learner_class)
        # host-side module copy for connectors (GAE bootstrap values)
        self._module = self.module_spec.build()
        if self.env_runner_group is not None:
            self.env_runner_group.sync_weights(self.learner_group.get_weights())

    def step(self) -> Dict[str, Any]:
        return self.training_step()

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self) -> Any:
        return {"learner": self.learner_group.get_state(), "config": None}

    def load_checkpoint(self, state: Any) -> None:
        self.learner_group.set_state(state["learner"])
        if self.env_runner_group is not None:
            self.env_runner_group.sync_weights(self.learner_group.get_weights())

    def cleanup(self) -> None:
        try:
            if self.env_runner_group is not None:
                self.env_runner_group.stop()
        finally:
            self.learner_group.shutdown()

    stop = cleanup  # reference Algorithm.stop()

    # -- convenience -----------------------------------------------------------
    def get_weights(self):
        return self.learner_group.get_weights()

    def evaluate(self, num_timesteps: int = 1000) -> Dict[str, Any]:
        if self.env_runner_group is None:
            return {"evaluation": {"episode_return_mean": None}}
        eps = self.env_runner_group.sample(num_timesteps, explore=False)
        rets = [float(e["rewards"].sum()) for e in eps if e["terminated"] or e["truncated"]]
        return {"evaluation": {"episode_return_mean": float(np.mean(rets)) if rets else None}}
