"""PPO: proximal policy optimization on the new API stack.

Capability parity: reference rllib/algorithms/ppo/ppo.py:362 (training_step :388) and
ppo_torch_learner's loss — clipped surrogate + value clip + entropy bonus; GAE in the
learner connector; weight sync back to env runners each iteration (ppo.py:452).
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..connectors import GeneralAdvantageEstimation
from ..core.learner import Learner
from ..core.rl_module import Columns
from .algorithm import Algorithm
from .algorithm_config import AlgorithmConfig


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or PPO)
        self.lambda_: float = 0.95
        self.clip_param: float = 0.3
        self.vf_clip_param: float = 10.0
        self.vf_loss_coeff: float = 1.0
        self.entropy_coeff: float = 0.0
        self.kl_coeff: float = 0.0  # ASHA-friendly default: pure clipping, no KL penalty
        self.use_gae: bool = True

    def training(self, *, lambda_=None, clip_param=None, vf_clip_param=None,
                 vf_loss_coeff=None, entropy_coeff=None, kl_coeff=None, **kwargs) -> "PPOConfig":
        for k, v in dict(lambda_=lambda_, clip_param=clip_param, vf_clip_param=vf_clip_param,
                         vf_loss_coeff=vf_loss_coeff, entropy_coeff=entropy_coeff, kl_coeff=kl_coeff).items():
            if v is not None:
                setattr(self, k, v)
        super().training(**kwargs)
        return self


class PPOLearner(Learner):
    """PPO loss in jax (reference ppo_torch_learner.compute_loss_for_module)."""

    def compute_losses(self, params, batch):
        import jax.numpy as jnp

        cfg = self.config
        out = self.module.forward_train(params, batch)
        dist = self.module.action_dist_cls
        logits = out[Columns.ACTION_DIST_INPUTS]
        logp = dist.logp_jax(logits, batch[Columns.ACTIONS])
        ratio = jnp.exp(logp - batch[Columns.ACTION_LOGP])
        adv = batch[Columns.ADVANTAGES]

        # decoupled trajectory blocks carry a validity mask (vector-env
        # autoreset rows); the serialized path has none -> plain means
        w = batch.get("loss_mask")
        if w is None:
            mmean = jnp.mean
        else:
            wsum = jnp.maximum(w.sum(), 1.0)

            def mmean(x):
                return (x * w).sum() / wsum

        surr1 = ratio * adv
        surr2 = jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param) * adv
        policy_loss = -mmean(jnp.minimum(surr1, surr2))

        vf = out[Columns.VF_PREDS]
        vf_err = jnp.square(vf - batch[Columns.VALUE_TARGETS])
        vf_loss = mmean(jnp.clip(vf_err, 0.0, cfg.vf_clip_param**2))

        entropy = mmean(dist.entropy_jax(logits))
        total = policy_loss + cfg.vf_loss_coeff * vf_loss - cfg.entropy_coeff * entropy
        aux = {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_kl": mmean(batch[Columns.ACTION_LOGP] - logp),
        }
        return total, aux


class PPO(Algorithm):
    learner_class = PPOLearner

    @classmethod
    def get_default_config(cls) -> PPOConfig:
        return PPOConfig(cls)

    def setup(self, _config) -> None:
        super().setup(_config)
        cfg = self._algo_config
        self._gae = GeneralAdvantageEstimation(cfg.gamma, cfg.lambda_)

    def training_step(self) -> Dict[str, Any]:
        cfg = self._algo_config
        if getattr(cfg, "decoupled", False):
            # decoupled rollout plane: learner-paced, GAE on device, blocks
            # stream over the zero-copy data plane (rllib/rollout_plane.py)
            return self._decoupled_training_step()
        # 1. synchronous parallel sampling (ppo.py:397)
        episodes = self.env_runner_group.sample(cfg.train_batch_size)
        if not episodes:
            # all runners died this iteration; they were restarted — skip the update
            return self.metrics.reduce()
        for m in self.env_runner_group.get_metrics():
            self.metrics.log_dict({k: v for k, v in m.items() if v is not None}, window=20)
        # 2. learner connector: GAE with host-side bootstrap values (ppo.py:425)
        params = self.learner_group.get_weights()
        batch = self._gae(episodes, module=self._module, params=params)
        # 3. sharded learner update
        learner_metrics = self.learner_group.update(batch)
        for lm in learner_metrics:
            self.metrics.log_dict(lm)
        # 4. sync new weights to env runners (ppo.py:452)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        result = self.metrics.reduce()
        result["num_env_steps_trained"] = len(batch[Columns.OBS])
        return result
