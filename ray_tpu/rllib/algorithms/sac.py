"""SAC: soft actor-critic for continuous control.

Capability parity: reference rllib/algorithms/sac/ (sac.py + sac_torch_learner's
twin-Q critic loss, reparameterized actor loss, auto-tuned temperature). One
jitted update computes all three losses; per-branch stop-gradients on parameter
leaves (not activations) keep each loss updating only its own network while the
reparameterized action gradient still flows through the critics into the policy.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..core.learner import Learner
from ..core.rl_module import SACModule
from ..utils.replay_buffer import ReplayBuffer
from .algorithm import Algorithm
from .algorithm_config import AlgorithmConfig


class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or SAC)
        self.rl_module_class = SACModule
        self.replay_buffer_capacity: int = 100_000
        self.num_steps_sampled_before_learning_starts: int = 500
        self.tau: float = 0.005  # polyak target update
        self.n_step: int = 1
        self.initial_alpha: float = 1.0
        self.target_entropy: str | float = "auto"  # auto = -act_dim
        # SAC wants ~1 gradient update per env step (reference training_intensity)
        self.num_updates_per_iteration: int = 256
        self.sample_timesteps_per_iteration: int = 256
        self.train_batch_size = 256
        self.lr = 3e-4
        self.num_epochs = 1

    def training(self, *, replay_buffer_capacity=None,
                 num_steps_sampled_before_learning_starts=None, tau=None,
                 n_step=None, initial_alpha=None, target_entropy=None,
                 num_updates_per_iteration=None,
                 sample_timesteps_per_iteration=None, **kwargs) -> "SACConfig":
        for k, v in dict(
            replay_buffer_capacity=replay_buffer_capacity,
            num_steps_sampled_before_learning_starts=num_steps_sampled_before_learning_starts,
            tau=tau, n_step=n_step, initial_alpha=initial_alpha,
            target_entropy=target_entropy,
            num_updates_per_iteration=num_updates_per_iteration,
            sample_timesteps_per_iteration=sample_timesteps_per_iteration,
        ).items():
            if v is not None:
                setattr(self, k, v)
        super().training(**kwargs)
        return self


class SACLearner(Learner):
    def build(self) -> None:
        import jax

        super().build()
        self.params["log_alpha"] = np.float32(np.log(self.config.initial_alpha))
        self.opt_state = self.optimizer.init(self.params)  # re-init with alpha set
        self.target_params = {
            "q1": jax.tree_util.tree_map(np.array, self.params["q1"]),
            "q2": jax.tree_util.tree_map(np.array, self.params["q2"]),
        }
        te = self.config.target_entropy
        self._target_entropy = float(-self.module.act_dim if te == "auto" else te)
        self._rng = jax.random.PRNGKey(self.config.seed or 0)

    def _build_update_fn(self):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        module = self.module

        def loss_fn(params, target_params, batch, rng, target_ent):
            sg = jax.lax.stop_gradient
            sg_tree = lambda t: jax.tree_util.tree_map(sg, t)  # noqa: E731
            r1, r2 = jax.random.split(rng)
            alpha = jnp.exp(params["log_alpha"])

            # critic loss: targets from target nets + current policy at s'
            next_a, next_logp = module.sample_action_jax(sg_tree(params), batch["next_obs"], r1)
            tq1 = module.q_jax(target_params, "q1", batch["next_obs"], next_a)
            tq2 = module.q_jax(target_params, "q2", batch["next_obs"], next_a)
            target_v = jnp.minimum(tq1, tq2) - sg(alpha) * next_logp
            target = sg(batch["rewards"]
                        + (cfg.gamma ** cfg.n_step) * (1.0 - batch["dones"]) * target_v)
            q1 = module.q_jax(params, "q1", batch["obs"], batch["actions"])
            q2 = module.q_jax(params, "q2", batch["obs"], batch["actions"])
            critic_loss = jnp.mean((q1 - target) ** 2) + jnp.mean((q2 - target) ** 2)

            # actor loss: reparameterized action through FROZEN critics
            frozen = {**params, "q1": sg_tree(params["q1"]), "q2": sg_tree(params["q2"])}
            a_new, logp = module.sample_action_jax(params, batch["obs"], r2)
            q_pi = jnp.minimum(module.q_jax(frozen, "q1", batch["obs"], a_new),
                               module.q_jax(frozen, "q2", batch["obs"], a_new))
            actor_loss = jnp.mean(sg(alpha) * logp - q_pi)

            # temperature: drive policy entropy toward the target
            alpha_loss = -jnp.mean(
                params["log_alpha"] * sg(logp + target_ent))

            total = critic_loss + actor_loss + alpha_loss
            aux = {
                "critic_loss": critic_loss,
                "actor_loss": actor_loss,
                "alpha_loss": alpha_loss,
                "alpha": alpha,
                "mean_q": jnp.mean(q1),
                "mean_logp": jnp.mean(logp),
            }
            return total, aux

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        @jax.jit
        def update(params, target_params, batch, rng, target_ent):
            (loss, aux), grads = grad_fn(params, target_params, batch, rng, target_ent)
            return loss, aux, grads

        return update

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        import jax
        import optax

        self._rng, sub = jax.random.split(self._rng)
        loss, aux, grads = self._update_fn(self.params, self.target_params, batch,
                                           sub, self._target_entropy)
        grads = self._sync_grads(grads)
        updates, self.opt_state = self.optimizer.update(grads, self.opt_state, self.params)
        self.params = optax.apply_updates(self.params, updates)
        self.params = jax.tree_util.tree_map(np.asarray, self.params)
        # polyak target update
        tau = self.config.tau
        for which in ("q1", "q2"):
            self.target_params[which] = jax.tree_util.tree_map(
                lambda t, p: np.asarray((1 - tau) * t + tau * p),
                self.target_params[which], self.params[which])
        self.metrics = {"total_loss": float(loss),
                        **{k: float(v) for k, v in aux.items()}}
        return self.metrics

    def get_state(self) -> Dict[str, Any]:
        return {"params": self.params, "opt_state": self.opt_state,
                "target_params": self.target_params}

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        if state.get("target_params") is not None:
            self.target_params = state["target_params"]


class SAC(Algorithm):
    learner_class = SACLearner

    @classmethod
    def get_default_config(cls) -> SACConfig:
        return SACConfig(cls)

    def setup(self, _config) -> None:
        super().setup(_config)
        cfg = self._algo_config
        self.buffer = ReplayBuffer(cfg.replay_buffer_capacity, n_step=cfg.n_step,
                                   gamma=cfg.gamma)
        self._rng = np.random.default_rng(cfg.seed or 0)
        self._env_steps = 0

    def save_checkpoint(self) -> Any:
        state = super().save_checkpoint()
        state["env_steps"] = self._env_steps
        return state

    def load_checkpoint(self, state: Any) -> None:
        super().load_checkpoint(state)
        self._env_steps = int(state.get("env_steps", 0))

    def training_step(self) -> Dict[str, Any]:
        cfg = self._algo_config
        episodes = self.env_runner_group.sample(cfg.sample_timesteps_per_iteration)
        self._env_steps += self.buffer.add_episodes(episodes)
        for m in self.env_runner_group.get_metrics():
            self.metrics.log_dict({k: v for k, v in m.items() if v is not None}, window=20)
        if len(self.buffer) >= cfg.num_steps_sampled_before_learning_starts:
            for _ in range(cfg.num_updates_per_iteration):
                batch = self.buffer.sample(cfg.train_batch_size, self._rng)
                for lm in self.learner_group.update(batch):
                    self.metrics.log_dict(lm)
            self.env_runner_group.sync_weights(self.learner_group.get_weights())
        result = self.metrics.reduce()
        result["num_env_steps_sampled_lifetime"] = self._env_steps
        return result
