"""DQN: deep Q-learning with target network, double-Q, and replay.

Capability parity: reference rllib/algorithms/dqn/ (dqn.py training_step —
sample → store → replay-train → target sync; dqn_rainbow_learner's huber TD loss
with double-Q). The update is one jitted value_and_grad step; the target network
is a second param tree passed as a jit argument (never a Python closure, so hard
target swaps don't retrace).
"""
from __future__ import annotations

import copy
from typing import Any, Dict

import numpy as np

from ..core.learner import Learner
from ..core.rl_module import DQNModule
from ..utils.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from .algorithm import Algorithm
from .algorithm_config import AlgorithmConfig


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or DQN)
        self.rl_module_class = DQNModule
        # off-policy knobs (reference DQNConfig.training surface)
        self.replay_buffer_capacity: int = 50_000
        self.prioritized_replay: bool = False
        self.num_steps_sampled_before_learning_starts: int = 1000
        self.target_network_update_freq: int = 200  # learner updates between hard syncs
        self.tau: float = 1.0  # 1.0 = hard sync; <1 = polyak every update
        self.double_q: bool = True
        self.n_step: int = 1
        self.epsilon: tuple = (1.0, 0.05)  # (initial, final)
        self.epsilon_timesteps: int = 10_000
        self.num_updates_per_iteration: int = 16
        self.sample_timesteps_per_iteration: int = 512
        # sensible off-policy defaults (the base defaults are PPO-shaped)
        self.train_batch_size = 64
        self.lr = 1e-3
        self.num_epochs = 1

    def training(self, *, replay_buffer_capacity=None, prioritized_replay=None,
                 num_steps_sampled_before_learning_starts=None,
                 target_network_update_freq=None, tau=None, double_q=None,
                 n_step=None, epsilon=None, epsilon_timesteps=None,
                 num_updates_per_iteration=None,
                 sample_timesteps_per_iteration=None, **kwargs) -> "DQNConfig":
        for k, v in dict(
            replay_buffer_capacity=replay_buffer_capacity,
            prioritized_replay=prioritized_replay,
            num_steps_sampled_before_learning_starts=num_steps_sampled_before_learning_starts,
            target_network_update_freq=target_network_update_freq, tau=tau,
            double_q=double_q, n_step=n_step, epsilon=epsilon,
            epsilon_timesteps=epsilon_timesteps,
            num_updates_per_iteration=num_updates_per_iteration,
            sample_timesteps_per_iteration=sample_timesteps_per_iteration,
        ).items():
            if v is not None:
                setattr(self, k, v)
        super().training(**kwargs)
        return self


class DQNLearner(Learner):
    """Huber TD loss with target network + optional double-Q (jitted)."""

    def build(self) -> None:
        import jax

        super().build()
        self.target_params = jax.tree_util.tree_map(np.array, self.params)
        self._updates_since_sync = 0

    def _build_update_fn(self):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        module = self.module

        def loss_fn(params, target_params, batch):
            q = module.q_values_jax(params, batch["obs"])  # [B, A]
            qa = jnp.take_along_axis(q, batch["actions"][:, None], axis=1)[:, 0]
            q_next_t = module.q_values_jax(target_params, batch["next_obs"])
            if cfg.double_q:
                # action selection by the online net, evaluation by the target net
                next_a = jnp.argmax(module.q_values_jax(params, batch["next_obs"]), axis=1)
                next_v = jnp.take_along_axis(q_next_t, next_a[:, None], axis=1)[:, 0]
            else:
                next_v = q_next_t.max(axis=1)
            # n-step: rewards are already the discounted n-step sum; bootstrap γ^n
            target = (batch["rewards"]
                      + (cfg.gamma ** cfg.n_step) * (1.0 - batch["dones"]) * next_v)
            td = qa - jax.lax.stop_gradient(target)
            huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td * td, jnp.abs(td) - 0.5)
            weights = batch.get("weights")
            loss = jnp.mean(huber * weights) if weights is not None else jnp.mean(huber)
            aux = {
                "mean_q": jnp.mean(qa),
                "mean_target": jnp.mean(target),
                "mean_td_error": jnp.mean(jnp.abs(td)),
                "td_errors": td,  # per-sample, for priority updates
            }
            return loss, aux

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        @jax.jit
        def update(params, target_params, batch):
            (loss, aux), grads = grad_fn(params, target_params, batch)
            return loss, aux, grads

        return update

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        import jax
        import optax

        jbatch = {k: v for k, v in batch.items() if k != "batch_indexes"}
        loss, aux, grads = self._update_fn(self.params, self.target_params, jbatch)
        grads = self._sync_grads(grads)
        updates, self.opt_state = self.optimizer.update(grads, self.opt_state, self.params)
        self.params = optax.apply_updates(self.params, updates)
        self.params = jax.tree_util.tree_map(np.asarray, self.params)

        # target sync: polyak each step (tau<1) or hard copy every N updates
        self._updates_since_sync += 1
        cfg = self.config
        if cfg.tau < 1.0:
            self.target_params = jax.tree_util.tree_map(
                lambda t, p: np.asarray((1 - cfg.tau) * t + cfg.tau * p),
                self.target_params, self.params)
        elif self._updates_since_sync >= cfg.target_network_update_freq:
            self.target_params = jax.tree_util.tree_map(np.array, self.params)
            self._updates_since_sync = 0

        td_errors = np.asarray(aux.pop("td_errors"))
        self.metrics = {"total_loss": float(loss),
                        **{k: float(v) for k, v in aux.items()}}
        # for prioritized replay: td errors with THIS learner's shard indexes
        # (the learner group shards batches, so indexes must travel together)
        self.metrics["_td_errors"] = td_errors
        if "batch_indexes" in batch:
            self.metrics["_batch_indexes"] = np.asarray(batch["batch_indexes"])
        return self.metrics

    def get_state(self) -> Dict[str, Any]:
        return {"params": self.params, "opt_state": self.opt_state,
                "target_params": self.target_params}

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        if state.get("target_params") is not None:
            self.target_params = state["target_params"]


class DQN(Algorithm):
    learner_class = DQNLearner

    @classmethod
    def get_default_config(cls) -> DQNConfig:
        return DQNConfig(cls)

    def setup(self, _config) -> None:
        super().setup(_config)
        cfg = self._algo_config
        buf_cls = PrioritizedReplayBuffer if cfg.prioritized_replay else ReplayBuffer
        self.buffer = buf_cls(cfg.replay_buffer_capacity, n_step=cfg.n_step,
                              gamma=cfg.gamma)
        self._rng = np.random.default_rng(cfg.seed or 0)
        self._env_steps = 0
        self._sync_epsilon()

    def _epsilon(self) -> float:
        e0, e1 = self._algo_config.epsilon
        frac = min(1.0, self._env_steps / max(1, self._algo_config.epsilon_timesteps))
        return float(e0 + (e1 - e0) * frac)

    def _sync_epsilon(self) -> None:
        w = dict(self.learner_group.get_weights())
        w["epsilon"] = np.float32(self._epsilon())
        self.env_runner_group.sync_weights(w)

    def save_checkpoint(self) -> Any:
        state = super().save_checkpoint()
        state["env_steps"] = self._env_steps  # epsilon schedule position
        return state

    def load_checkpoint(self, state: Any) -> None:
        super().load_checkpoint(state)
        self._env_steps = int(state.get("env_steps", 0))
        self._sync_epsilon()  # undo the raw-weight sync's stale epsilon leaf

    def training_step(self) -> Dict[str, Any]:
        cfg = self._algo_config
        # 1. sample with the current epsilon, store transitions (dqn.py sample phase)
        episodes = self.env_runner_group.sample(cfg.sample_timesteps_per_iteration)
        added = self.buffer.add_episodes(episodes)
        self._env_steps += added
        for m in self.env_runner_group.get_metrics():
            self.metrics.log_dict({k: v for k, v in m.items() if v is not None}, window=20)

        # 2. replay-train once warm (dqn.py update phase)
        if len(self.buffer) >= cfg.num_steps_sampled_before_learning_starts:
            for _ in range(cfg.num_updates_per_iteration):
                batch = self.buffer.sample(cfg.train_batch_size, self._rng)
                for lm in self.learner_group.update(batch):
                    td = lm.pop("_td_errors", None)
                    idx = lm.pop("_batch_indexes", None)
                    if td is not None and idx is not None:
                        self.buffer.update_priorities(idx, td)
                    self.metrics.log_dict(lm)

        # 3. decayed epsilon + fresh weights to the runners
        self._sync_epsilon()
        result = self.metrics.reduce()
        result["num_env_steps_sampled_lifetime"] = self._env_steps
        result["epsilon"] = self._epsilon()
        return result
