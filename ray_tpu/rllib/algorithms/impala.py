"""IMPALA: importance-weighted async actor-learner architecture with V-trace.

Capability parity: reference rllib/algorithms/impala/impala.py:142 — async sampling
from env-runner actors (in-flight sample() refs collected with wait()), optional
aggregator actors (`num_aggregator_actors_per_learner`, impala.py:507,635) that pad
episode chunks into fixed-shape time-major batches, V-trace off-policy correction
(impala loss; Espeholt et al. 2018), and periodic (not per-update) weight broadcast
(`broadcast_interval`). TPU-first: the V-trace correction + policy/value/entropy loss
is one jitted program — the reverse-time recursion is a `lax.scan`, shapes are padded
to (bucketed B, max_seq_len) so XLA compile caches stay warm.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

from ..core.learner import Learner
from ..core.rl_module import Columns
from ..utils.gae import vtrace_scan
from .algorithm import Algorithm
from .algorithm_config import AlgorithmConfig

import logging

logger = logging.getLogger("ray_tpu.rllib.impala")


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or IMPALA)
        self.vtrace_clip_rho_threshold: float = 1.0
        self.vtrace_clip_pg_rho_threshold: float = 1.0
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.005
        self.broadcast_interval: int = 1  # learner updates between weight broadcasts
        self.num_aggregator_actors_per_learner: int = 0
        self.max_seq_len: int = 64  # pad/split episode chunks to this length
        self.num_epochs = 1  # IMPALA is single-pass
        self.minibatch_size = None

    def training(self, *, vtrace_clip_rho_threshold=None, vtrace_clip_pg_rho_threshold=None,
                 vf_loss_coeff=None, entropy_coeff=None, broadcast_interval=None,
                 num_aggregator_actors_per_learner=None, max_seq_len=None, **kwargs):
        for k, v in dict(
            vtrace_clip_rho_threshold=vtrace_clip_rho_threshold,
            vtrace_clip_pg_rho_threshold=vtrace_clip_pg_rho_threshold,
            vf_loss_coeff=vf_loss_coeff, entropy_coeff=entropy_coeff,
            broadcast_interval=broadcast_interval,
            num_aggregator_actors_per_learner=num_aggregator_actors_per_learner,
            max_seq_len=max_seq_len,
        ).items():
            if v is not None:
                setattr(self, k, v)
        super().training(**kwargs)
        return self


def _split_episode(ep: Dict[str, np.ndarray], max_T: int) -> List[Dict[str, np.ndarray]]:
    """Split an episode chunk into <=max_T pieces; interior pieces bootstrap."""
    T = len(ep["rewards"])
    if T <= max_T:
        return [ep]
    out = []
    for s in range(0, T, max_T):
        e = s + min(max_T, T - s)
        last = e == T
        piece = {
            "obs": ep["obs"][s:e],
            # boundary obs: next chunk's first obs doubles as this chunk's bootstrap obs
            "next_obs_last": ep["next_obs_last"] if last else ep["obs"][e],
            "actions": ep["actions"][s:e],
            "rewards": ep["rewards"][s:e],
            "terminated": ep["terminated"] and last,
            "truncated": ep["truncated"] and last,
        }
        for k in (Columns.ACTION_LOGP, Columns.VF_PREDS):
            if k in ep:
                piece[k] = ep[k][s:e]
        out.append(piece)
    return out


def pad_time_major(episodes: List[Dict[str, np.ndarray]], max_T: int, b_bucket: int = 8) -> Dict[str, np.ndarray]:
    """Pad episode chunks into fixed-shape arrays (the aggregator's job).

    Returns batch-major arrays: obs_ext [B, T+1, D] (row `lens[b]` holds the bootstrap
    obs), actions [B, T], behaviour logp / rewards / mask [B, T], lens + terminated [B].
    B is rounded up to a multiple of `b_bucket` (mask-zero rows) so XLA sees few
    distinct shapes.
    """
    pieces: List[Dict[str, np.ndarray]] = []
    for ep in episodes:
        pieces.extend(_split_episode(ep, max_T))
    B = len(pieces)
    Bp = ((B + b_bucket - 1) // b_bucket) * b_bucket
    obs_dim = int(np.prod(pieces[0]["obs"].shape[1:]))
    act_shape = pieces[0]["actions"].shape[1:]
    obs_ext = np.zeros((Bp, max_T + 1, obs_dim), np.float32)
    actions = np.zeros((Bp, max_T) + act_shape, pieces[0]["actions"].dtype)
    logp = np.zeros((Bp, max_T), np.float32)
    rewards = np.zeros((Bp, max_T), np.float32)
    mask = np.zeros((Bp, max_T), np.float32)
    lens = np.zeros(Bp, np.int32)
    terminated = np.zeros(Bp, np.float32)
    for b, p in enumerate(pieces):
        T = len(p["rewards"])
        obs_ext[b, :T] = p["obs"].reshape(T, -1)
        obs_ext[b, T] = np.asarray(p["next_obs_last"]).reshape(-1)
        actions[b, :T] = p["actions"]
        logp[b, :T] = np.asarray(p[Columns.ACTION_LOGP], np.float32)
        rewards[b, :T] = p["rewards"]
        mask[b, :T] = 1.0
        lens[b] = T
        terminated[b] = float(bool(p["terminated"]))
    return {
        "obs_ext": obs_ext, "actions": actions, "behaviour_logp": logp,
        "rewards": rewards, "mask": mask, "lens": lens, "terminated": terminated,
    }


class Aggregator:
    """Batching actor (reference impala.py num_aggregator_actors_per_learner)."""

    def __init__(self, max_T: int):
        self.max_T = max_T

    def aggregate(self, episodes: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
        return pad_time_major(episodes, self.max_T)

    def ping(self) -> bool:
        return True


class IMPALALearner(Learner):
    """V-trace actor-critic loss, one jitted step per padded batch."""

    def compute_losses(self, params, batch):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        B, Tp1, D = batch["obs_ext"].shape
        T = Tp1 - 1
        flat = batch["obs_ext"].reshape(B * Tp1, D)
        out = self.module.forward_train(params, {Columns.OBS: flat})
        dist = self.module.action_dist_cls
        logits = out[Columns.ACTION_DIST_INPUTS].reshape(B, Tp1, -1)
        values_ext = out[Columns.VF_PREDS].reshape(B, Tp1)
        mask = batch["mask"]
        lens = batch["lens"]
        # bootstrap value lives at row lens[b] of the extended sequence
        bootstrap = jnp.take_along_axis(values_ext, lens[:, None], axis=1)[:, 0]
        bootstrap = bootstrap * (1.0 - batch["terminated"])
        values = values_ext[:, :T] * mask

        step_logits = logits[:, :T].reshape(B * T, -1)
        step_actions = batch["actions"].reshape((B * T,) + batch["actions"].shape[2:])
        target_logp = dist.logp_jax(step_logits, step_actions).reshape(B, T) * mask
        entropy = dist.entropy_jax(step_logits).reshape(B, T)

        rhos = jnp.exp(target_logp - batch["behaviour_logp"] * mask)
        clipped_rho = jnp.minimum(cfg.vtrace_clip_rho_threshold, rhos) * mask
        cs = jnp.minimum(1.0, rhos) * mask
        # terminal step gets discount 0; padded steps contribute nothing
        is_last = (jnp.arange(T)[None, :] == (lens - 1)[:, None]).astype(jnp.float32)
        discounts = cfg.gamma * (1.0 - is_last * batch["terminated"][:, None]) * mask
        v_tp1 = jnp.concatenate([values[:, 1:], jnp.zeros((B, 1))], axis=1)
        # at t = len-1 the next value is the bootstrap, not values[t+1] (which is padding)
        v_tp1 = v_tp1 + is_last * bootstrap[:, None]
        deltas = clipped_rho * (batch["rewards"] + discounts * v_tp1 - values)
        vs_minus_v = vtrace_scan(deltas.T, discounts.T, cs.T).T  # [B, T]
        vs = values + vs_minus_v
        vs_tp1 = jnp.concatenate([vs[:, 1:], jnp.zeros((B, 1))], axis=1) + is_last * bootstrap[:, None]
        clipped_pg_rho = jnp.minimum(cfg.vtrace_clip_pg_rho_threshold, rhos) * mask
        pg_adv = jax.lax.stop_gradient(
            clipped_pg_rho * (batch["rewards"] + discounts * vs_tp1 - values)
        )

        n = jnp.maximum(mask.sum(), 1.0)
        mean_kl = ((batch["behaviour_logp"] * mask - target_logp) * mask).sum() / n
        pg_loss = self._pg_loss(target_logp, batch["behaviour_logp"] * mask, pg_adv, mask, n,
                                batch.get("kl_coeff", 0.0))
        vf_loss = 0.5 * (jnp.square(jax.lax.stop_gradient(vs) - values) * mask).sum() / n
        ent = (entropy * mask).sum() / n
        total = pg_loss + cfg.vf_loss_coeff * vf_loss - cfg.entropy_coeff * ent
        return total, {
            "policy_loss": pg_loss, "vf_loss": vf_loss, "entropy": ent,
            "mean_rho": (rhos * mask).sum() / n, "mean_kl": mean_kl,
        }

    def _pg_loss(self, target_logp, behaviour_logp, pg_adv, mask, n, kl_coeff):
        """Vanilla importance-weighted policy gradient (APPO overrides with a clip)."""
        return -(target_logp * pg_adv).sum() / n

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """Whole-batch updates on one padded batch; num_epochs extra passes are
        off-policy-corrected by V-trace (rhos grow as the policy drifts)."""
        import jax
        import optax

        for _ in range(max(1, self.config.num_epochs)):
            loss, aux, grads = self._update_fn(self.params, batch)
            grads = self._sync_grads(grads)
            updates, self.opt_state = self.optimizer.update(grads, self.opt_state, self.params)
            self.params = optax.apply_updates(self.params, updates)
        self.params = jax.tree_util.tree_map(lambda a: np.asarray(a), self.params)
        self.metrics = {"total_loss": float(loss), **{k: float(v) for k, v in aux.items()}}
        return self.metrics


class IMPALA(Algorithm):
    learner_class = IMPALALearner

    @classmethod
    def get_default_config(cls) -> IMPALAConfig:
        return IMPALAConfig(cls)

    def setup(self, _config) -> None:
        super().setup(_config)
        cfg = self._algo_config
        self._inflight: Dict[Any, int] = {}  # sample ref -> runner index
        self._updates_since_broadcast = 0
        self._aggregators = []
        n_agg = cfg.num_aggregator_actors_per_learner * max(1, cfg.num_learners)
        if n_agg:
            agg_cls = ray_tpu.remote(num_cpus=1)(Aggregator)
            self._aggregators = [agg_cls.remote(cfg.max_seq_len) for _ in range(n_agg)]
        self._agg_rr = 0

    def _issue(self, idx: int) -> None:
        per = max(1, self._algo_config.train_batch_size // self.env_runner_group.n)
        ref = self.env_runner_group.runners[idx].sample.remote(per, True)
        self._inflight[ref] = idx

    def _aggregate(self, episodes: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
        if self._aggregators:
            agg = self._aggregators[self._agg_rr % len(self._aggregators)]
            self._agg_rr += 1
            return ray_tpu.get(agg.aggregate.remote(episodes))
        return pad_time_major(episodes, self._algo_config.max_seq_len)

    def training_step(self) -> Dict[str, Any]:
        cfg = self._algo_config
        group = self.env_runner_group
        if not self._inflight:
            for i in range(group.n):
                self._issue(i)
        # async collect: take whatever finished first, keep the rest in flight
        episodes: List[Dict[str, np.ndarray]] = []
        steps = 0
        while steps < cfg.train_batch_size:
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1, timeout=30.0)
            if not ready:
                break
            for ref in ready:
                idx = self._inflight.pop(ref)
                try:
                    eps = ray_tpu.get(ref)
                except Exception as e:
                    logger.warning("env runner %d failed a rollout (%r); "
                                   "restarting it", idx, e)
                    group.restart_runner(idx)
                    self._issue(idx)
                    continue
                episodes.extend(eps)
                steps += sum(len(e["rewards"]) for e in eps)
                self._issue(idx)
        if not episodes:
            return self.metrics.reduce()
        for m in group.get_metrics():
            self.metrics.log_dict({k: v for k, v in m.items() if v is not None}, window=20)
        batch = self._aggregate(episodes)
        learner_metrics = self.learner_group.update(batch)
        for lm in learner_metrics:
            self.metrics.log_dict(lm)
        self._updates_since_broadcast += 1
        if self._updates_since_broadcast >= cfg.broadcast_interval:
            group.sync_weights(self.learner_group.get_weights())
            self._updates_since_broadcast = 0
        result = self.metrics.reduce()
        result["num_env_steps_trained"] = steps
        return result

    def cleanup(self) -> None:
        for ref in list(self._inflight):
            try:
                ray_tpu.cancel(ref)
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass
        self._inflight.clear()
        for a in self._aggregators:
            try:
                ray_tpu.kill(a)
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass
        super().cleanup()

    stop = cleanup
