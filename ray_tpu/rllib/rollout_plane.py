"""Decoupled actor–learner rollout plane (Podracer-style, arXiv 2104.06272).

The serialized `Algorithm.training_step` interleaves rollout, host GAE, and
the learner update — each phase idles the others. This module decouples them:

- `VectorizedRolloutWorker`: an actor pool member that steps N envs as one
  stacked call and writes **fixed-size trajectory blocks** straight into the
  object store (`create_raw` → fill the numpy views in place → `seal`), then
  announces a ~1 KB `BlockHandle` to the `BlockQueue`. Block payloads never
  ride an actor RPC and never touch the head.
- `BlockQueue`: a bounded queue actor. When full it evicts the oldest block
  (freshest-data wins); blocks staler than the learner by more than
  `RAY_TPU_RL_MAX_BLOCK_LAG` policy versions are dropped at take time. It
  also piggybacks block-release acks and the latest weights-broadcast
  metadata onto announce responses, so workers need no extra control RPCs.
- `RolloutPlane`: the driver facade — spawns the pool, polls the queue for
  the learner, routes releases, and accounts every admitted block so a clean
  shutdown can assert **zero leaked block admissions**.

Learners consume blocks via `read_block_arrays`: same-host blocks are adopted
through `try_map_local` + `read_pinned` (no pickle, no copy through the
plane); cross-host falls back to striped `pull_into` range reads from the
worker's data plane. Policy weights flow the other way as a versioned
broadcast (`rlwts:<version>` on the lead learner's plane); workers pick up
the newest version between blocks and never block mid-episode.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import (ObjectLost, create_raw, free_local,
                                       read_pinned, try_map_local)
from ray_tpu.rllib.core.rl_module import Columns
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner
from ray_tpu.util import telemetry
from ray_tpu.util.collective import ring

_ALIGN = 64
_MIN_STRIPE = 1 << 20  # below this, striping overhead beats the parallelism


# --------------------------------------------------------------- param codec

def _iter_leaves(tree):
    """Deterministic traversal (dicts by sorted key, sequences by index)."""
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _iter_leaves(tree[k])
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            yield from _iter_leaves(v)
    else:
        yield tree


def pack_params(tree) -> bytes:
    """Flatten a params tree to one contiguous byte buffer (leaf order is the
    deterministic traversal, so any process holding a structurally identical
    tree can unpack without a schema exchange)."""
    parts = []
    for leaf in _iter_leaves(tree):
        parts.append(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return b"".join(parts)


def unpack_params_like(tree, buf) -> Any:
    """Rebuild a tree structured like `tree` with leaf values read from `buf`
    (the inverse of pack_params against the receiver's own params tree)."""
    mv = memoryview(buf)
    off = 0

    def rebuild(node):
        nonlocal off
        if isinstance(node, dict):
            out = dict(node)
            for k in sorted(node):
                out[k] = rebuild(node[k])
            return out
        if isinstance(node, (list, tuple)):
            vals = [rebuild(v) for v in node]
            return tuple(vals) if isinstance(node, tuple) else vals
        a = np.asarray(node)
        n = a.nbytes
        out = np.frombuffer(mv[off:off + n], dtype=a.dtype).reshape(a.shape)
        off += n
        return out.copy()

    return rebuild(tree)


# ---------------------------------------------------------------- block spec

@dataclasses.dataclass(frozen=True)
class TrajectoryBlockSpec:
    """Fixed [T, B] time-major layout of one trajectory block.

    `obs` is [T+1, B, *obs_shape] in the env's NATIVE dtype (uint8 atari
    frames ship at 1 byte/px): row t+1 is row t's next observation — under
    gymnasium 1.x next-step autoreset that makes a done row's successor the
    episode's true final observation, so bootstraps need no side table.
    `valid` marks real transitions (0 = the vector env's autoreset row).
    """
    T: int
    B: int
    obs_shape: Tuple[int, ...]
    obs_dtype: str
    act_shape: Tuple[int, ...]
    act_dtype: str

    def fields(self) -> List[Tuple[str, Tuple[int, ...], str]]:
        f32, u8 = "float32", "uint8"
        return [
            ("obs", (self.T + 1, self.B) + tuple(self.obs_shape), self.obs_dtype),
            ("actions", (self.T, self.B) + tuple(self.act_shape), self.act_dtype),
            ("action_logp", (self.T, self.B), f32),
            ("rewards", (self.T, self.B), f32),
            ("vf_preds", (self.T, self.B), f32),
            ("boot_values", (self.T, self.B), f32),
            ("terminated", (self.T, self.B), u8),
            ("truncated", (self.T, self.B), u8),
            ("valid", (self.T, self.B), u8),
        ]

    def layout(self) -> Tuple[List[Tuple[str, int, Tuple[int, ...], str]], int]:
        out, off = [], 0
        for name, shape, dtype in self.fields():
            out.append((name, off, shape, dtype))
            nb = int(np.prod(shape)) * np.dtype(dtype).itemsize
            off = (off + nb + _ALIGN - 1) & ~(_ALIGN - 1)
        return out, off

    @property
    def nbytes(self) -> int:
        return self.layout()[1]

    def views(self, mv: memoryview) -> Dict[str, np.ndarray]:
        """Numpy views over a block buffer — zero-copy in both directions."""
        fields, _ = self.layout()
        out = {}
        for name, off, shape, dtype in fields:
            nb = int(np.prod(shape)) * np.dtype(dtype).itemsize
            out[name] = np.frombuffer(mv[off:off + nb], dtype=dtype).reshape(shape)
        return out


@dataclasses.dataclass
class BlockHandle:
    """The ~1 KB announcement for one sealed trajectory block."""
    worker_index: int
    generation: int
    seq: int
    location: tuple
    addr: Tuple[str, int]
    key: str
    spec: TrajectoryBlockSpec
    policy_version: int
    env_steps: int
    episode_returns: Tuple[float, ...]

    @property
    def uid(self) -> Tuple[int, int, int]:
        return (self.worker_index, self.generation, self.seq)


def pull_key_into(plane, addr, key: str, out_mv: memoryview, *,
                  timeout: float = 120.0, probe_s: float = 0.5,
                  streams: int = 4) -> None:
    """Striped ranged pull of a published key into a preallocated buffer.

    Bounded-probe loop per stripe (the mpmd StageComm idiom): a `pull_into`
    miss returns None with nothing written, we re-probe until the deadline.
    """
    total = len(out_mv)
    deadline = time.monotonic() + timeout
    n_str = max(1, min(streams, total // _MIN_STRIPE or 1))
    base = total // n_str
    spans = [(i * base, base if i < n_str - 1 else total - (n_str - 1) * base)
             for i in range(n_str)]

    def pull_span(off: int, ln: int) -> None:
        while True:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"pull of {key!r} from {addr} timed out after {timeout}s")
            try:
                n = plane.pull_into(addr, key, off, ln, out_mv[off:off + ln],
                                    timeout=probe_s)
            except (OSError, ConnectionError):
                time.sleep(min(probe_s, 0.2))
                continue
            if n is not None:
                return

    if n_str == 1:
        pull_span(*spans[0])
        return
    with ThreadPoolExecutor(max_workers=n_str - 1) as ex:
        futs = [ex.submit(pull_span, o, ln) for o, ln in spans[1:]]
        pull_span(*spans[0])
        for f in futs:
            f.result()


def read_block_arrays(handle: BlockHandle, plane=None, *,
                      timeout: float = 120.0,
                      adopt: bool = False) -> Dict[str, np.ndarray]:
    """Land a block's arrays in this process: same-host mapped adoption of
    the sealed object (no pickle, no transfer) with a striped `pull_into`
    fallback from the announcing worker's data plane.

    With ``adopt=True`` (mapped path only) the dominant ``obs`` field is
    returned as a zero-copy VIEW of the pinned mapping and the pin rides
    along under the ``"_pin"`` key — the caller must pop and ``release()``
    it only after the update has fully consumed obs. Small fields are
    copied out either way."""
    spec = handle.spec
    pulls = telemetry.get_counter("rl_block_pulls_total", tag_keys=("path",))
    if try_map_local(handle.location):
        pr = read_pinned(handle.location, 0, spec.nbytes)
        if adopt:
            out = {k: (v if k == "obs" else np.array(v))
                   for k, v in spec.views(pr.view).items()}
            out["_pin"] = pr
            pulls.inc(1, {"path": "mapped"})
            return out
        try:
            # copy out: the consumer feeds jax, which on the CPU backend may
            # alias a donated numpy buffer past the pin's release
            out = {k: np.array(v) for k, v in spec.views(pr.view).items()}
        finally:
            pr.release()
        pulls.inc(1, {"path": "mapped"})
        return out
    if plane is None:
        raise ObjectLost(f"block {handle.key} is remote and no plane was given")
    buf = np.empty(spec.nbytes, np.uint8)
    pull_key_into(plane, tuple(handle.addr), handle.key, memoryview(buf),
                  timeout=timeout)
    pulls.inc(1, {"path": "striped"})
    return spec.views(memoryview(buf))


# --------------------------------------------------------------- block queue

class BlockQueue:
    """Bounded block-handle queue actor + weights mailbox + release router.

    Accounting invariant (the leak gate): every announced block ends up in
    exactly one of {taken, expired, reaped}, and its seq is routed back to
    its worker for release (or reaped by the driver when the worker died).
    """

    def __init__(self, max_depth: int = 8, max_lag: int = 4):
        self._max_depth = int(max_depth)
        self._max_lag = int(max_lag)
        self._q: deque = deque()
        self._release: Dict[int, List[int]] = {}
        self._pending: Dict[Tuple[int, int, int], BlockHandle] = {}
        self._weights: Optional[Tuple[int, Tuple[str, int], int]] = None
        self._stop = False
        self._counts = {"announced": 0, "taken": 0, "expired": 0,
                        "released": 0, "reaped": 0}
        self._lag_max_taken = 0  # worst staleness ever trained on
        self._taken_lag_counts: Dict[int, int] = {}  # lag -> taken blocks
        self._blocks = telemetry.get_counter(
            "rl_blocks_total", tag_keys=("event",))
        self._depth_gauge = telemetry.get_gauge("rl_queue_depth")
        self._lag_hist = telemetry.get_histogram(
            "rl_block_lag", boundaries=[0, 1, 2, 3, 4, 6, 8, 12, 16, 32])

    def _expire(self, handle: BlockHandle) -> None:
        self._counts["expired"] += 1
        self._blocks.inc(1, {"event": "expired"})
        self._release.setdefault(handle.worker_index, []).append(handle.seq)

    def announce(self, handle: BlockHandle) -> Dict[str, Any]:
        if not self._stop:
            while len(self._q) >= self._max_depth:
                old = self._q.popleft()
                self._pending.pop(old.uid, None)
                self._expire(old)
            self._q.append(handle)
            self._pending[handle.uid] = handle
            self._counts["announced"] += 1
            self._blocks.inc(1, {"event": "announced"})
        else:
            # shutting down: admit nothing; tell the worker to free it
            self._release.setdefault(handle.worker_index, []).append(handle.seq)
        self._depth_gauge.set(float(len(self._q)))
        return {
            "released": self._release.pop(handle.worker_index, []),
            "weights": self._weights,
            "stop": self._stop,
            "depth": len(self._q),
        }

    def take(self, max_n: int, learner_version: int) -> List[BlockHandle]:
        out: List[BlockHandle] = []
        while self._q and len(out) < max_n:
            h = self._q.popleft()
            lag = max(0, learner_version - h.policy_version)
            self._lag_hist.observe(float(lag))
            if lag > self._max_lag:
                self._pending.pop(h.uid, None)
                self._expire(h)
                continue
            out.append(h)
            self._counts["taken"] += 1
            self._lag_max_taken = max(self._lag_max_taken, lag)
            self._taken_lag_counts[lag] = self._taken_lag_counts.get(lag, 0) + 1
            self._blocks.inc(1, {"event": "taken"})
        self._depth_gauge.set(float(len(self._q)))
        return out

    def release(self, uids: List[Tuple[int, int, int]]) -> None:
        """Learner is done with these blocks; route the seqs home."""
        for uid in uids:
            h = self._pending.pop(tuple(uid), None)
            if h is not None:
                self._counts["released"] += 1
                self._release.setdefault(h.worker_index, []).append(h.seq)

    def reap_worker(self, worker_index: int) -> List[BlockHandle]:
        """A worker died: hand its un-freed blocks to the driver for cleanup."""
        dead = [h for h in self._pending.values()
                if h.worker_index == worker_index]
        for h in dead:
            self._pending.pop(h.uid, None)
            try:
                self._q.remove(h)
            except ValueError:
                pass
            self._counts["reaped"] += 1
        self._release.pop(worker_index, None)
        self._depth_gauge.set(float(len(self._q)))
        return dead

    def set_weights(self, version: int, addr, nbytes: int) -> None:
        self._weights = (int(version), tuple(addr), int(nbytes))
        telemetry.get_counter("rl_weight_broadcasts_total").inc()

    def request_stop(self) -> None:
        self._stop = True
        while self._q:
            h = self._q.popleft()
            self._pending.pop(h.uid, None)
            self._expire(h)
        self._depth_gauge.set(0.0)

    def stats(self) -> Dict[str, Any]:
        c = dict(self._counts)
        c["depth"] = len(self._q)
        c["unreleased"] = len(self._pending)
        c["lag_max_taken"] = self._lag_max_taken
        c["lag_p99_taken"] = self._lag_quantile(0.99)
        c["max_lag"] = self._max_lag
        c["outstanding"] = (c["announced"] - c["taken"] - c["expired"]
                            - c["reaped"])
        return c

    def _lag_quantile(self, q: float) -> Optional[int]:
        """Exact quantile of the staleness of TAKEN (trained-on) blocks —
        integer lags make the full distribution a tiny counts dict."""
        total = sum(self._taken_lag_counts.values())
        if not total:
            return None
        need = q * total
        run = 0
        for lag in sorted(self._taken_lag_counts):
            run += self._taken_lag_counts[lag]
            if run >= need:
                return lag
        return self._lag_max_taken

    def ping(self) -> bool:
        return True


# ------------------------------------------------------------ rollout worker

class VectorizedRolloutWorker(SingleAgentEnvRunner):
    """Env-runner that streams sealed trajectory blocks from a background
    rollout loop instead of returning episode lists over RPC."""

    def __init__(self, config, worker_index: int, authkey: bytes, queue,
                 generation: int = 0):
        super().__init__(config, worker_index=worker_index)
        self._authkey = authkey
        self._queue = queue
        self._generation = int(generation)
        self._plane = None
        self._spec: Optional[TrajectoryBlockSpec] = None
        self._seq = 0
        self._policy_version = 0
        self._blocks: Dict[int, Tuple[tuple, Any, str]] = {}
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ep_ret = np.zeros(self.num_envs, np.float64)
        self._recent_returns: deque = deque(maxlen=64)
        self._steps_total = 0
        self._blocks_built = 0
        self._last_error: Optional[str] = None

    # -- layout ---------------------------------------------------------------
    def _build_spec(self) -> TrajectoryBlockSpec:
        import gymnasium as gym

        T = int(getattr(self.config, "decoupled_block_T", None)
                or self.config.rollout_fragment_length)
        obs_space = self.env.single_observation_space
        act_space = self.env.single_action_space
        if isinstance(act_space, gym.spaces.Discrete):
            act_shape, act_dtype = (), "int32"
        else:
            act_shape, act_dtype = tuple(act_space.shape), "float32"
        return TrajectoryBlockSpec(
            T=T, B=self.num_envs, obs_shape=tuple(obs_space.shape),
            obs_dtype=str(np.dtype(obs_space.dtype)), act_shape=act_shape,
            act_dtype=act_dtype)

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> bool:
        if self._thread is not None:
            return True
        self._plane = ring.get_plane(self._authkey, min_streams=2)
        self._spec = self._build_spec()
        self._thread = threading.Thread(
            target=self._run, name=f"rollout-worker-{self.worker_index}",
            daemon=True)
        self._thread.start()
        return True

    def _run(self) -> None:
        slack = int(getattr(self.config, "producer_slack", 2))
        try:
            while not self._stop_evt.is_set():
                t0 = time.monotonic()
                handle = self._build_block()
                build_s = time.monotonic() - t0
                resp = ray_tpu.get(self._queue.announce.remote(handle))
                for seq in resp.get("released", ()):
                    self._free_block(seq)
                w = resp.get("weights")
                if w is not None and w[0] > self._policy_version:
                    self._apply_weights(*w)
                if resp.get("stop"):
                    break
                # producer backpressure: a queue holding more than `slack`
                # un-taken blocks means we are outrunning the learner — every
                # further block is CPU burned on data that will be evicted.
                # Pace by the excess, in units of our own build time, so the
                # pool equilibrates near the slack depth (slack <= 0: off).
                excess = resp.get("depth", 0) - slack
                if slack > 0 and excess > 0:
                    self._stop_evt.wait(min(excess * build_s, 2.0))
        except Exception as e:  # noqa: BLE001 — thread boundary: recorded, surfaced via health()
            self._last_error = f"{type(e).__name__}: {e}"

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30.0)
        for seq in list(self._blocks):
            self._free_block(seq)
        super().stop()

    def health(self) -> Dict[str, Any]:
        return {
            "alive": bool(self._thread and self._thread.is_alive()),
            "error": self._last_error,
            "outstanding": len(self._blocks),
            "policy_version": self._policy_version,
        }

    def outstanding(self) -> int:
        return len(self._blocks)

    def get_metrics(self) -> Dict[str, Any]:
        rets = list(self._recent_returns)
        return {
            "num_env_steps_sampled": self._steps_total,
            "episode_return_mean": float(np.mean(rets)) if rets else None,
            "num_episodes": len(rets),
            "num_blocks": self._blocks_built,
            "policy_version": self._policy_version,
        }

    # -- block production -----------------------------------------------------
    def _build_block(self) -> BlockHandle:
        spec = self._spec
        with telemetry.span("rl.rollout_block", "rl",
                            worker=self.worker_index, seq=self._seq):
            self._reset_if_needed()
            oid = ObjectID.generate()
            tgt = create_raw(oid, spec.nbytes)
            views = spec.views(tgt.view)
            dist = self.module.action_dist_cls
            valid_steps = 0
            returns_done: List[float] = []
            for t in range(spec.T):
                out = self.module.forward_exploration(
                    self.params, {Columns.OBS: self._obs})
                vf = out[Columns.VF_PREDS]
                if t > 0:
                    views["boot_values"][t - 1] = vf
                dist_inputs = out[Columns.ACTION_DIST_INPUTS]
                actions = dist.sample_np(dist_inputs, self.rng)
                logp = dist.logp_np(dist_inputs, actions)
                was_done = self._prev_done.copy()
                views["obs"][t] = self._obs
                views["actions"][t] = actions
                views["action_logp"][t] = logp
                views["vf_preds"][t] = vf
                views["valid"][t] = (~was_done).astype(np.uint8)
                obs, rewards, terms, truncs, _ = self.env.step(actions)
                views["rewards"][t] = rewards
                views["terminated"][t] = np.asarray(terms).astype(np.uint8)
                views["truncated"][t] = np.asarray(truncs).astype(np.uint8)
                live = ~was_done
                self._ep_ret = np.where(
                    was_done, 0.0, self._ep_ret + np.asarray(rewards))
                done_now = np.asarray(terms) | np.asarray(truncs)
                for r in self._ep_ret[live & done_now]:
                    returns_done.append(float(r))
                    self._recent_returns.append(float(r))
                valid_steps += int(live.sum())
                # next-step autoreset: a row that followed a done row was the
                # reset itself — its done flags can't be set again
                self._prev_done = live & done_now
                self._obs = obs
            views["obs"][spec.T] = self._obs
            out = self.module.forward_exploration(
                self.params, {Columns.OBS: self._obs})
            views["boot_values"][spec.T - 1] = out[Columns.VF_PREDS]
            views = None  # drop buffer refs before seal releases the view
            loc = tgt.seal()
            pinned = read_pinned(loc, 0, spec.nbytes)
            key = f"rlblk:{self.worker_index}:{self._generation}:{self._seq}"
            self._plane.publish(key, pinned.view, expected_read_bytes=0)
            self._blocks[self._seq] = (loc, pinned, key)
            handle = BlockHandle(
                worker_index=self.worker_index, generation=self._generation,
                seq=self._seq, location=loc, addr=tuple(self._plane.addr),
                key=key, spec=spec, policy_version=self._policy_version,
                env_steps=valid_steps,
                episode_returns=tuple(returns_done[-16:]))
            self._seq += 1
            self._blocks_built += 1
            self._steps_total += valid_steps
            telemetry.get_counter("rl_env_steps_total").inc(valid_steps)
            return handle

    def _free_block(self, seq: int) -> None:
        ent = self._blocks.pop(seq, None)
        if ent is None:
            return
        loc, pinned, key = ent
        try:
            self._plane.retract(key)
        # graftlint: allow[swallowed-exception] plane may already be torn down at shutdown
        except Exception:
            pass
        try:
            pinned.release()
        # graftlint: allow[swallowed-exception] view may already be released by a racing stop
        except Exception:
            pass
        try:
            free_local(loc)
        # graftlint: allow[swallowed-exception] backing may already be freed by the reaper
        except Exception:
            pass

    # -- weights --------------------------------------------------------------
    def _apply_weights(self, version: int, addr, nbytes: int) -> None:
        buf = np.empty(nbytes, np.uint8)
        pull_key_into(self._plane, tuple(addr), f"rlwts:{version}",
                      memoryview(buf), timeout=60.0)
        self.params = unpack_params_like(self.params, buf)
        self._policy_version = int(version)


# -------------------------------------------------------------------- driver

class RolloutPlane:
    """Driver facade over the queue + worker pool."""

    def __init__(self, config, *, authkey: Optional[bytes] = None):
        import os

        self.config = config
        self.authkey = authkey or os.urandom(16)
        depth = int(getattr(config, "decoupled_queue_depth", 8))
        max_lag = int(getattr(config, "max_block_lag", 4))
        self._queue_cls = ray_tpu.remote(num_cpus=0)(BlockQueue)
        self.queue = self._queue_cls.remote(depth, max_lag)
        self._worker_cls = ray_tpu.remote(num_cpus=1)(VectorizedRolloutWorker)
        self._generations = [0] * config.num_env_runners
        self.workers = [
            self._worker_cls.remote(config, i, self.authkey, self.queue)
            for i in range(config.num_env_runners)
        ]
        ray_tpu.get([w.start.remote() for w in self.workers])
        self._reaped_locs = 0

    def take(self, max_n: int, learner_version: int,
             timeout_s: float = 30.0) -> List[BlockHandle]:
        deadline = time.monotonic() + timeout_s
        while True:
            handles = ray_tpu.get(
                self.queue.take.remote(max_n, learner_version))
            if handles or time.monotonic() > deadline:
                return handles
            time.sleep(0.02)

    def release(self, handles: List[BlockHandle]) -> None:
        self.queue.release.remote([h.uid for h in handles])

    def set_weights(self, version: int, addr, nbytes: int) -> None:
        self.queue.set_weights.remote(version, addr, nbytes)

    def worker_metrics(self) -> List[Dict[str, Any]]:
        out = []
        for w in self.workers:
            if w is None:
                continue
            try:
                out.append(ray_tpu.get(w.get_metrics.remote()))
            # graftlint: allow[swallowed-exception] dead workers are expected under chaos; pool backfills
            except Exception:
                continue
        return out

    def reap_worker(self, i: int) -> int:
        """Free a dead worker's un-released blocks from the driver (same-host
        arena/shm backings survive the worker process) and account them."""
        dead = ray_tpu.get(self.queue.reap_worker.remote(i))
        freed = 0
        for h in dead:
            try:
                free_local(h.location)
                freed += 1
            # graftlint: allow[swallowed-exception] remote or already-freed backing; accounting still records the reap
            except Exception:
                continue
        self._reaped_locs += freed
        self.workers[i] = None
        return len(dead)

    def restart_worker(self, i: int) -> None:
        """Backfill the pool slot with a fresh worker (new generation)."""
        old = self.workers[i]
        if old is not None:
            try:
                ray_tpu.kill(old)
            # graftlint: allow[swallowed-exception] worker already dead — that is why we are restarting it
            except Exception:
                pass
            self.reap_worker(i)
        self._generations[i] += 1
        w = self._worker_cls.remote(self.config, i, self.authkey, self.queue,
                                    self._generations[i])
        ray_tpu.get(w.start.remote())
        self.workers[i] = w

    def stats(self) -> Dict[str, Any]:
        s = ray_tpu.get(self.queue.stats.remote())
        outstanding = 0
        for w in self.workers:
            if w is None:
                continue
            try:
                outstanding += ray_tpu.get(w.outstanding.remote())
            # graftlint: allow[swallowed-exception] dead worker: its blocks are accounted via reap_worker
            except Exception:
                continue
        s["worker_outstanding"] = outstanding
        s["reaped_freed"] = self._reaped_locs
        return s

    def shutdown(self) -> Dict[str, Any]:
        try:
            ray_tpu.get(self.queue.request_stop.remote())
        # graftlint: allow[swallowed-exception] queue already dead; workers will notice on announce
        except Exception:
            pass
        for i, w in enumerate(self.workers):
            if w is None:
                continue
            try:
                ray_tpu.get(w.stop.remote())
            # graftlint: allow[swallowed-exception] dead worker at shutdown: blocks were reaped or will be
            except Exception:
                pass
        stats = {}
        try:
            stats = self.stats()
        # graftlint: allow[swallowed-exception] stats are best-effort once actors are going away
        except Exception:
            pass
        for w in self.workers:
            if w is None:
                continue
            try:
                ray_tpu.kill(w)
            # graftlint: allow[swallowed-exception] already dead
            except Exception:
                pass
        try:
            ray_tpu.kill(self.queue)
        # graftlint: allow[swallowed-exception] already dead
        except Exception:
            pass
        return stats
