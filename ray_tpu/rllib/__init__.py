"""ray_tpu.rllib: JAX-first reinforcement learning.

Capability parity: reference rllib/ new API stack — Algorithm/AlgorithmConfig,
Learner/LearnerGroup, RLModule, EnvRunner(Group), ConnectorV2, PPO.
"""
from .algorithms.algorithm import Algorithm  # noqa: F401
from .algorithms.algorithm_config import AlgorithmConfig  # noqa: F401
from .algorithms.appo import APPO, APPOConfig, APPOLearner  # noqa: F401
from .algorithms.cql import CQL, CQLConfig, CQLLearner  # noqa: F401
from .algorithms.dqn import DQN, DQNConfig, DQNLearner  # noqa: F401
from .algorithms.dreamerv3 import DreamerV3, DreamerV3Config, DreamerV3Learner  # noqa: F401
from .algorithms.impala import IMPALA, IMPALAConfig, IMPALALearner  # noqa: F401
from .algorithms.marwil import BC, BCConfig, MARWIL, MARWILConfig, MARWILLearner  # noqa: F401
from .algorithms.multi_agent_ppo import MultiAgentPPO, MultiAgentPPOConfig  # noqa: F401
from .algorithms.ppo import PPO, PPOConfig, PPOLearner  # noqa: F401
from .algorithms.sac import SAC, SACConfig, SACLearner  # noqa: F401
from .connectors import ConnectorPipelineV2, ConnectorV2, GeneralAdvantageEstimation  # noqa: F401
from .core.learner import Learner  # noqa: F401
from .core.learner_group import LearnerGroup  # noqa: F401
from .core.rl_module import Columns, MLPModule, RLModule, RLModuleSpec  # noqa: F401
from .core.multi_learner import MultiAgentLearner  # noqa: F401
from .env.env_runner import SingleAgentEnvRunner  # noqa: F401
from .env.env_runner_group import EnvRunnerGroup  # noqa: F401
from .env.episode import SingleAgentEpisode  # noqa: F401
from .env.multi_agent_env import MultiAgentEnv, make_multi_agent  # noqa: F401
from .env.multi_agent_env_runner import MultiAgentEnvRunner, MultiAgentEpisode  # noqa: F401
from .offline import OfflineData, OfflinePreLearner  # noqa: F401
from .utils.metrics_logger import MetricsLogger  # noqa: F401
