"""ConnectorV2 pipelines.

Capability parity: reference rllib/connectors/{env_to_module,module_to_env,learner}/ —
composable transforms between env, module, and learner. The learner pipeline implements
GAE (general_advantage_estimation.py) and batching of episode lists.
"""
from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .core.rl_module import Columns


class ConnectorV2:
    def __call__(self, data: Any, **kwargs) -> Any:
        raise NotImplementedError


class ConnectorPipelineV2(ConnectorV2):
    def __init__(self, connectors: List[ConnectorV2]):
        self.connectors = list(connectors)

    def __call__(self, data: Any, **kwargs) -> Any:
        for c in self.connectors:
            data = c(data, **kwargs)
        return data

    def append(self, c: ConnectorV2) -> None:
        self.connectors.append(c)


class FlattenObs(ConnectorV2):
    """env->module: flatten observations to [B, -1] float32."""

    def __call__(self, batch: Dict[str, np.ndarray], **kw) -> Dict[str, np.ndarray]:
        obs = batch[Columns.OBS]
        batch[Columns.OBS] = obs.reshape(len(obs), -1).astype(np.float32)
        return batch


class GeneralAdvantageEstimation(ConnectorV2):
    """learner pipeline: per-episode GAE(lambda) + value targets, then concat.

    Reference rllib/connectors/learner/general_advantage_estimation.py. Episodes not
    terminated bootstrap from the module's value of the last observation.
    """

    def __init__(self, gamma: float, lambda_: float):
        self.gamma = gamma
        self.lambda_ = lambda_

    def __call__(self, episodes: List[Dict[str, np.ndarray]], *, module=None, params=None, **kw) -> Dict[str, np.ndarray]:
        batches = []
        for ep in episodes:
            T = len(ep["rewards"])
            vf = np.asarray(ep[Columns.VF_PREDS], np.float32)
            rewards = ep["rewards"]
            if ep["terminated"]:
                bootstrap = 0.0
            else:
                out = module.apply_np(params, ep["next_obs_last"][None])
                bootstrap = float(out[Columns.VF_PREDS][0])
            vf_ext = np.append(vf, bootstrap)
            adv = np.zeros(T, np.float32)
            gae = 0.0
            for t in range(T - 1, -1, -1):
                delta = rewards[t] + self.gamma * vf_ext[t + 1] - vf_ext[t]
                gae = delta + self.gamma * self.lambda_ * gae
                adv[t] = gae
            targets = adv + vf
            batches.append({
                Columns.OBS: ep["obs"],
                Columns.ACTIONS: ep["actions"],
                Columns.ACTION_LOGP: np.asarray(ep[Columns.ACTION_LOGP], np.float32),
                Columns.VF_PREDS: vf,
                Columns.ADVANTAGES: adv,
                Columns.VALUE_TARGETS: targets.astype(np.float32),
            })
        out: Dict[str, np.ndarray] = {}
        for k in batches[0]:
            out[k] = np.concatenate([b[k] for b in batches])
        # standardize advantages across the whole train batch (reference ppo default)
        a = out[Columns.ADVANTAGES]
        out[Columns.ADVANTAGES] = (a - a.mean()) / max(a.std(), 1e-6)
        obs = out[Columns.OBS]
        out[Columns.OBS] = obs.reshape(len(obs), -1).astype(np.float32)
        return out
