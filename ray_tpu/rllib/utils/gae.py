"""Device-resident advantage estimation: GAE and the V-trace recursion as
`lax.scan`s over a trajectory block's time axis.

The serialized PPO path computes GAE on the host — a per-episode reverse
Python loop inside `connectors.GeneralAdvantageEstimation` — which serializes
rollout, advantage pass, and learner update. The decoupled rollout plane
(`rllib/rollout_plane.py`) ships fixed-shape [T, B] time-major trajectory
blocks instead, and these kernels fold the advantage pass INTO the jitted
learner update: one scan over the block's time axis, no host round-trip.

Parity contract (tests/test_gae_scan.py): `gae_scan` is bit-close (f32) to
the host-numpy pass across episode boundaries, truncation bootstraps, and
`lambda_` in {0, 0.95, 1}. Episode boundaries inside a block are carried by
the `terminated`/`truncated` row flags — the recursion resets across a done
row exactly like the host loop's per-episode restart.
"""
from __future__ import annotations

from ray_tpu.util.hot_path import hot_path


@hot_path(reason="inside the jitted decoupled learner update; pure lax.scan")
def gae_scan(rewards, values, boot_values, terminated, truncated, *,
             gamma: float, lambda_: float):
    """GAE(lambda) over a time-major trajectory block.

    All inputs are [T, B] (f32; the flags may be bool/uint8):

    - ``rewards[t, b]``     reward of step t in column b
    - ``values[t, b]``      behaviour-policy V(obs_t)
    - ``boot_values[t, b]`` behaviour-policy V(obs_{t+1}) — the NEXT
      observation's value, which at an episode's last row is the value of the
      true final observation (gymnasium 1.x next-step autoreset returns it)
    - ``terminated[t, b]``  env terminated at step t (bootstrap masked to 0)
    - ``truncated[t, b]``   env truncated at step t (bootstraps from
      boot_values, but the accumulation chain still resets)

    Returns ``(advantages, value_targets)``, both [T, B] f32. Rows marked
    invalid by the caller (autoreset rows) come out as garbage and must be
    masked in the loss — the chain is already broken at the preceding done
    row, so they never contaminate a real row.
    """
    import jax
    import jax.numpy as jnp

    rewards = jnp.asarray(rewards, jnp.float32)
    values = jnp.asarray(values, jnp.float32)
    boot = jnp.asarray(boot_values, jnp.float32)
    term = jnp.asarray(terminated, jnp.float32)
    done = jnp.maximum(term, jnp.asarray(truncated, jnp.float32))

    deltas = rewards + gamma * (1.0 - term) * boot - values
    cont = (1.0 - done) * gamma * lambda_

    def backward(acc, xs):
        delta_t, cont_t = xs
        acc = delta_t + cont_t * acc
        return acc, acc

    _, adv = jax.lax.scan(
        backward, jnp.zeros(rewards.shape[1], jnp.float32),
        (deltas, cont), reverse=True)
    return adv, adv + values


@hot_path(reason="shared V-trace core: one reverse scan, no host syncs")
def vtrace_scan(deltas, discounts, cs):
    """The V-trace reverse-time recursion (Espeholt et al. 2018, eq. 1):

        acc_t = delta_t + discount_t * c_t * acc_{t+1}

    over time-major [T, B] inputs; returns ``vs - V`` as [T, B]. This is the
    exact scan IMPALA's learner ran inline — extracted so the decoupled
    rollout plane's "vtrace" off-policy correction and IMPALALearner share
    one implementation (both are bit-identical to the previous inline form:
    same op sequence, same zero init).
    """
    import jax
    import jax.numpy as jnp

    def backward(acc, xs):
        delta_t, disc_t, c_t = xs
        acc = delta_t + disc_t * c_t * acc
        return acc, acc

    _, out = jax.lax.scan(
        backward, jnp.zeros(deltas.shape[1], deltas.dtype),
        (deltas, discounts, cs), reverse=True)
    return out


def vtrace_block(rewards, values, boot_values, terminated, truncated, rhos,
                 *, gamma: float, lambda_: float = 1.0,
                 clip_rho_threshold: float = 1.0,
                 clip_pg_rho_threshold: float = 1.0):
    """V-trace targets + policy-gradient advantages for a [T, B] block.

    ``values``/``boot_values`` are the CURRENT policy's value estimates of
    obs_t / obs_{t+1} (recomputed on device by the decoupled learner), and
    ``rhos`` the per-step importance ratios pi_cur/pi_behaviour. Episode
    boundaries (done rows) cut the recursion; the row after a boundary starts
    a fresh chain. At a block's last row (and at done rows) the next-state
    target falls back to the bootstrap value — the off-policy tail
    approximation the staleness bound keeps small.

    Returns ``(pg_advantages, value_targets)``, both [T, B] f32, both
    stop-gradiented.
    """
    import jax
    import jax.numpy as jnp

    rewards = jnp.asarray(rewards, jnp.float32)
    values = jnp.asarray(values, jnp.float32)
    term = jnp.asarray(terminated, jnp.float32)
    done = jnp.maximum(term, jnp.asarray(truncated, jnp.float32))
    v_next = jnp.asarray(boot_values, jnp.float32) * (1.0 - term)

    clipped_rho = jnp.minimum(clip_rho_threshold, rhos)
    cs = lambda_ * jnp.minimum(1.0, rhos)
    discounts = gamma * (1.0 - done)
    # v_next carries the truncation bootstrap and zeroes out at termination,
    # so this is delta_t = rho_clip * (r + gamma*V(s_{t+1}) - V(s_t)) with
    # the recursion itself cut at done rows by `discounts`.
    deltas = clipped_rho * (rewards + gamma * v_next - values)
    vs_minus_v = vtrace_scan(deltas, discounts, cs)
    vs = values + vs_minus_v
    # next-step target for the pg advantage: vs_{t+1} within a chain, the
    # bootstrap value across a boundary / at the block tail
    vs_next = jnp.concatenate([vs[1:], v_next[-1:]], axis=0)
    vs_next = jnp.where(done > 0, v_next, vs_next)
    clipped_pg_rho = jnp.minimum(clip_pg_rho_threshold, rhos)
    pg_adv = clipped_pg_rho * (rewards + gamma * vs_next - values)
    return jax.lax.stop_gradient(pg_adv), jax.lax.stop_gradient(vs)
