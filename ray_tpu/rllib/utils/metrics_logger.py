"""MetricsLogger: hierarchical stat aggregation (reference rllib/utils/metrics/metrics_logger.py:18)."""
from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List, Optional

import numpy as np


class MetricsLogger:
    def __init__(self):
        self._values: Dict[str, List[float]] = defaultdict(list)
        self._windows: Dict[str, int] = {}

    def log_value(self, key: str, value: Any, window: Optional[int] = None, reduce: str = "mean") -> None:
        if value is None:
            return
        self._values[key].append(float(value))
        if window:
            self._windows[key] = window
            self._values[key] = self._values[key][-window:]

    def log_dict(self, d: Dict[str, Any], prefix: str = "", **kw) -> None:
        for k, v in d.items():
            if isinstance(v, (int, float, np.floating, np.integer)):
                self.log_value(prefix + k, v, **kw)

    def peek(self, key: str, default=None):
        vals = self._values.get(key)
        return float(np.mean(vals)) if vals else default

    def reduce(self) -> Dict[str, float]:
        out = {}
        for k, vals in self._values.items():
            if vals:
                out[k] = float(np.mean(vals))
        # windowed stats persist across iterations; point stats reset
        for k in list(self._values):
            if k not in self._windows:
                self._values[k] = []
        return out
