"""Replay buffers for off-policy algorithms.

Capability parity: reference rllib/utils/replay_buffers/ (EpisodeReplayBuffer,
PrioritizedEpisodeReplayBuffer) — transition-level storage in preallocated numpy
rings so sampled batches are contiguous arrays ready for one jitted update.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class ReplayBuffer:
    """Uniform transition replay (ring buffer) with optional n-step returns.

    With n_step > 1 each stored transition is (obs_t, a_t, sum_{k<n} γ^k r_{t+k},
    obs_{t+n}, done-within-window); the learner then bootstraps with γ^n.
    """

    def __init__(self, capacity: int = 100_000, n_step: int = 1, gamma: float = 0.99):
        self.capacity = capacity
        self.n_step = max(1, n_step)
        self.gamma = gamma
        self._storage: Optional[Dict[str, np.ndarray]] = None
        self._idx = 0
        self._size = 0

    def __len__(self) -> int:
        return self._size

    def _ensure_storage(self, obs: np.ndarray, actions: np.ndarray) -> None:
        if self._storage is not None:
            return
        obs_shape = obs.shape[1:]
        # action dtype/shape follow the env: int64 scalars (DQN) or float vectors (SAC)
        self._storage = {
            "obs": np.zeros((self.capacity, *obs_shape), obs.dtype),
            "next_obs": np.zeros((self.capacity, *obs_shape), obs.dtype),
            "actions": np.zeros((self.capacity, *actions.shape[1:]), actions.dtype),
            "rewards": np.zeros((self.capacity,), np.float32),
            "dones": np.zeros((self.capacity,), np.float32),
        }

    def _ring_write(self, rows: Dict[str, np.ndarray], t: int) -> None:
        """Write t rows at the ring head with at most two slice assignments/key."""
        first = min(t, self.capacity - self._idx)
        for k, v in rows.items():
            self._storage[k][self._idx:self._idx + first] = v[:first]
            if first < t:
                self._storage[k][: t - first] = v[first:]
        self._idx = (self._idx + t) % self.capacity
        self._size = min(self._size + t, self.capacity)

    def add_episodes(self, episodes: List[Dict[str, np.ndarray]]) -> int:
        """Ingest env-runner episode dicts (episode.py to_numpy format)."""
        added = 0
        n, g = self.n_step, self.gamma
        for ep in episodes:
            obs = ep["obs"]
            t = len(ep["actions"])
            if t == 0:
                continue
            all_obs = np.concatenate([obs, ep["next_obs_last"][None]], axis=0)  # T+1
            rewards = np.asarray(ep["rewards"], np.float32)
            terminal = bool(ep["terminated"])
            # n-step aggregation (window clips at the episode end; only a true
            # terminal inside the window sets done — truncation keeps bootstrapping)
            nr = np.zeros(t, np.float32)
            next_idx = np.minimum(np.arange(t) + n, t)
            for k in range(n):
                valid = np.arange(t) + k < t
                nr[valid] += (g**k) * rewards[k:][: valid.sum()]
            dones = np.zeros(t, np.float32)
            if terminal:
                dones[max(0, t - n):] = 1.0
            actions = np.asarray(ep["actions"])
            if actions.dtype.kind in "iu":
                actions = actions.astype(np.int64)
            rows = {
                "obs": obs,
                "next_obs": all_obs[next_idx],
                "actions": actions,
                "rewards": nr,
                "dones": dones,
            }
            self._ensure_storage(obs, actions)
            if t > self.capacity:  # only the last `capacity` rows can survive anyway
                rows = {k: v[t - self.capacity:] for k, v in rows.items()}
                t = self.capacity
            self._ring_write(rows, t)
            added += t
        return added

    def sample(self, batch_size: int, rng: np.random.Generator) -> Dict[str, np.ndarray]:
        if self._size == 0:
            raise ValueError("cannot sample from an empty replay buffer")
        idx = rng.integers(0, self._size, size=batch_size)
        return {k: v[idx] for k, v in self._storage.items()}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (Schaul et al.) with IS weights.

    O(n) sampling via cumulative sums — fine for the capacities used here; the
    reference's segment-tree variant is an optimization, not a semantic change.
    """

    def __init__(self, capacity: int = 100_000, n_step: int = 1, gamma: float = 0.99,
                 alpha: float = 0.6, beta: float = 0.4):
        super().__init__(capacity, n_step, gamma)
        self.alpha = alpha
        self.beta = beta
        self._priorities = np.zeros((capacity,), np.float32)
        self._max_priority = 1.0

    def add_episodes(self, episodes: List[Dict[str, np.ndarray]]) -> int:
        start = self._idx
        added = super().add_episodes(episodes)
        if added:
            idx = (start + np.arange(added)) % self.capacity
            self._priorities[idx] = self._max_priority
        return added

    def sample(self, batch_size: int, rng: np.random.Generator) -> Dict[str, np.ndarray]:
        if self._size == 0:
            raise ValueError("cannot sample from an empty replay buffer")
        p = self._priorities[: self._size] ** self.alpha
        p = p / p.sum()
        idx = rng.choice(self._size, size=batch_size, p=p)
        batch = {k: v[idx] for k, v in self._storage.items()}
        w = (self._size * p[idx]) ** (-self.beta)
        batch["weights"] = (w / w.max()).astype(np.float32)
        batch["batch_indexes"] = idx.astype(np.int64)
        return batch

    def update_priorities(self, indexes: np.ndarray, td_errors: np.ndarray) -> None:
        prios = np.abs(td_errors) + 1e-6
        self._priorities[indexes] = prios
        self._max_priority = max(self._max_priority, float(prios.max()))
