from ray_tpu.rllib.utils.gae import gae_scan, vtrace_block, vtrace_scan

__all__ = ["gae_scan", "vtrace_block", "vtrace_scan"]
