"""MultiAgentLearner: one learner actor owning a module per policy id.

Capability parity: reference rllib/core/rl_module/multi_rl_module.py +
learner.py's per-module loss loop — here each policy id gets an independent
sub-learner (own params/optimizer/jitted update); updates run module-by-module
in deterministic dict order so multi-learner collective grad syncs stay aligned
across actors.
"""
from __future__ import annotations

from typing import Any, Dict

from .learner import Learner


class MultiAgentLearner(Learner):
    def __init__(self, config: "AlgorithmConfig", module_specs: Dict[str, Any]):  # noqa: F821
        self.config = config
        self.module_specs = module_specs
        base = config.base_learner_class
        self._subs: Dict[str, Learner] = {
            mid: base(config, spec) for mid, spec in sorted(module_specs.items())
        }

    def build(self) -> None:
        for sub in self._subs.values():
            sub.build()

    def setup_collective(self, group_name: str) -> None:
        for sub in self._subs.values():
            sub.setup_collective(group_name)

    def update(self, batches: Dict[str, Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
        return {mid: self._subs[mid].update(b) for mid, b in sorted(batches.items())}

    def get_weights(self):
        return {mid: sub.get_weights() for mid, sub in self._subs.items()}

    def get_state(self) -> Dict[str, Any]:
        return {mid: sub.get_state() for mid, sub in self._subs.items()}

    def set_state(self, state: Dict[str, Any]) -> None:
        for mid, s in state.items():
            if mid in self._subs:
                self._subs[mid].set_state(s)

    def ping(self) -> bool:
        return True
