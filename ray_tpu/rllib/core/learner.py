"""Learner / JaxLearner: the gradient-update unit.

Capability parity: reference rllib/core/learner/learner.py:108 (compute_losses :893,
update :978) and torch/torch_learner.py:67. TPU-first: instead of torch autograd + DDP
wrapping (torch_learner.py:523), the update is one jitted jax.value_and_grad step with
optax; multi-learner gradient sync is an allreduce over the ray_tpu collective group
(ICI/XLA analog of the reference's NCCL allreduce).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .rl_module import Columns, RLModuleSpec


from ray_tpu.util.collective import CollectiveActorMixin


class Learner(CollectiveActorMixin):
    """Owns one RLModule's params + optimizer; subclass defines the loss."""

    def __init__(self, config: "AlgorithmConfig", module_spec: RLModuleSpec):  # noqa: F821
        self.config = config
        self.module_spec = module_spec
        self.module = module_spec.build()
        self._group_name: Optional[str] = None
        self.metrics: Dict[str, Any] = {}

    def build(self) -> None:
        import optax

        # params/opt_state stay DEVICE-RESIDENT between updates: fetching them
        # to host every update() (and re-uploading every minibatch) costs more
        # than the update itself on real accelerators — brutal via a network
        # tunnel. get_weights/get_state materialize numpy on demand.
        self.params = self.module.init_params(seed=self.config.seed or 0)
        clip = self.config.grad_clip
        tx = [optax.clip_by_global_norm(clip)] if clip else []
        tx.append(optax.adam(self.config.lr))
        self.optimizer = optax.chain(*tx)
        self.opt_state = self.optimizer.init(self.params)
        self._update_fn = self._build_update_fn()
        self._fused_update_fn = self._build_fused_update_fn()

    # -- to be provided by algo-specific learners ------------------------------
    def compute_losses(self, params, batch: Dict[str, Any]):
        """Return (total_loss, aux_metrics_dict) as jax scalars."""
        raise NotImplementedError

    def _build_update_fn(self):
        import jax

        def loss_fn(params, batch):
            loss, aux = self.compute_losses(params, batch)
            return loss, aux

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        @jax.jit
        def update(params, batch):
            (loss, aux), grads = grad_fn(params, batch)
            return loss, aux, grads

        return update

    def _build_fused_update_fn(self):
        """Single-learner fast path: loss -> grads -> optax -> new params in
        ONE jitted program (one device dispatch per minibatch). Multi-learner
        keeps the split path so the grad allreduce can run between."""
        import jax
        import optax

        def loss_fn(params, batch):
            loss, aux = self.compute_losses(params, batch)
            return loss, aux

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        @jax.jit
        def step(params, opt_state, batch):
            (loss, aux), grads = grad_fn(params, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        return step

    # -- collective group (multi-learner DDP analog) ---------------------------
    def setup_collective(self, group_name: str) -> None:
        self._group_name = group_name

    def _sync_grads(self, grads):
        if self._group_name is None:
            return grads
        import jax

        from ray_tpu.util import collective as col

        leaves, treedef = jax.tree_util.tree_flatten(grads)
        flat = np.concatenate([np.asarray(l).ravel() for l in leaves])
        reduced = col.allreduce(flat, group_name=self._group_name)
        reduced = reduced / col.get_collective_group_size(self._group_name)
        out, off = [], 0
        for l in leaves:
            n = int(np.prod(np.shape(l)))
            out.append(np.asarray(reduced[off : off + n]).reshape(np.shape(l)))
            off += n
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- update ---------------------------------------------------------------
    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """One pass of minibatch SGD epochs over the batch (learner.py:_update)."""
        import jax

        n = len(batch[Columns.OBS])
        mb = self.config.minibatch_size or n
        epochs = self.config.num_epochs
        rng = np.random.default_rng(0)
        losses, aux_out = [], {}
        mb = min(mb, n)
        for _ in range(epochs):
            perm = rng.permutation(n)
            # full minibatches only: constant shapes keep one jit trace
            for start in range(0, n - mb + 1, mb):
                idx = perm[start : start + mb]
                mbatch = {k: v[idx] for k, v in batch.items() if isinstance(v, np.ndarray) and len(v) == n}
                if self._group_name is not None:
                    loss, aux, grads = self._update_fn(self.params, mbatch)
                    grads = self._sync_grads(grads)
                    updates, self.opt_state = self.optimizer.update(
                        grads, self.opt_state, self.params)
                    import optax

                    self.params = optax.apply_updates(self.params, updates)
                else:
                    self.params, self.opt_state, loss, aux = self._fused_update_fn(
                        self.params, self.opt_state, mbatch)
                losses.append(loss)
                aux_out = aux
        # ONE host sync for the whole update, after every minibatch dispatched
        self.metrics = {
            "total_loss": float(np.mean([float(l) for l in losses])),
            **{k: float(v) for k, v in aux_out.items()},
        }
        return self.metrics

    # -- state ----------------------------------------------------------------
    def _host_params(self):
        import jax

        return jax.tree_util.tree_map(lambda a: np.asarray(a), self.params)

    def get_state(self) -> Dict[str, Any]:
        import jax

        return {"params": self._host_params(),
                "opt_state": jax.tree_util.tree_map(lambda a: np.asarray(a),
                                                    self.opt_state)}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        if state.get("opt_state") is not None:
            self.opt_state = state["opt_state"]

    def get_weights(self):
        return self._host_params()

    def ping(self) -> bool:
        return True
