"""Learner / JaxLearner: the gradient-update unit.

Capability parity: reference rllib/core/learner/learner.py:108 (compute_losses :893,
update :978) and torch/torch_learner.py:67. TPU-first: instead of torch autograd + DDP
wrapping (torch_learner.py:523), the update is one jitted jax.value_and_grad step with
optax; multi-learner gradient sync is an allreduce over the ray_tpu collective group
(ICI/XLA analog of the reference's NCCL allreduce).
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .rl_module import Columns, RLModuleSpec


from ray_tpu.util import telemetry
from ray_tpu.util.collective import CollectiveActorMixin
from ray_tpu.util.hot_path import hot_path


class Learner(CollectiveActorMixin):
    """Owns one RLModule's params + optimizer; subclass defines the loss."""

    def __init__(self, config: "AlgorithmConfig", module_spec: RLModuleSpec):  # noqa: F821
        self.config = config
        self.module_spec = module_spec
        self.module = module_spec.build()
        self._group_name: Optional[str] = None
        self.metrics: Dict[str, Any] = {}

    def build(self) -> None:
        import optax

        # params/opt_state stay DEVICE-RESIDENT between updates: fetching them
        # to host every update() (and re-uploading every minibatch) costs more
        # than the update itself on real accelerators — brutal via a network
        # tunnel. get_weights/get_state materialize numpy on demand.
        self.params = self.module.init_params(seed=self.config.seed or 0)
        clip = self.config.grad_clip
        tx = [optax.clip_by_global_norm(clip)] if clip else []
        tx.append(optax.adam(self.config.lr))
        self.optimizer = optax.chain(*tx)
        self.opt_state = self.optimizer.init(self.params)
        self._update_fn = self._build_update_fn()
        self._fused_update_fn = self._build_fused_update_fn()
        self._gather_update_fn = self._build_gather_update_fn()
        self._prepare_fn = None
        self._plane = None
        self._weights_version = 0

    # -- to be provided by algo-specific learners ------------------------------
    def compute_losses(self, params, batch: Dict[str, Any]):
        """Return (total_loss, aux_metrics_dict) as jax scalars."""
        raise NotImplementedError

    @staticmethod
    def _cast_obs(batch):
        """Cast OBS to f32 at the minibatch level, inside jit. Trajectory
        blocks carry obs in the env's native dtype (uint8 atari frames) all
        the way to the minibatch step — casting a 128-row gather is free,
        materializing the full block as f32 is 4x the memory traffic. On an
        already-f32 batch (the serialized path) the cast is a no-op."""
        import jax.numpy as jnp

        if Columns.OBS in batch:
            batch = dict(batch)
            batch[Columns.OBS] = batch[Columns.OBS].astype(jnp.float32)
        return batch

    def _build_update_fn(self):
        import jax

        def loss_fn(params, batch):
            loss, aux = self.compute_losses(params, batch)
            return loss, aux

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        @jax.jit
        def update(params, batch):
            (loss, aux), grads = grad_fn(params, self._cast_obs(batch))
            return loss, aux, grads

        return update

    def _build_fused_update_fn(self):
        """Single-learner fast path: loss -> grads -> optax -> new params in
        ONE jitted program (one device dispatch per minibatch). Multi-learner
        keeps the split path so the grad allreduce can run between."""
        import jax
        import optax

        def loss_fn(params, batch):
            loss, aux = self.compute_losses(params, batch)
            return loss, aux

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        @jax.jit
        def step(params, opt_state, batch):
            (loss, aux), grads = grad_fn(params, self._cast_obs(batch))
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        return step

    def _build_gather_update_fn(self):
        """Device-resident minibatch SGD: the batch is uploaded ONCE per
        update and the ENTIRE epoch schedule — every epoch's permuted
        [steps, mb] index matrix — runs as one jitted lax.scan with
        (params, opt_state) as carry and on-device gathers (`v[ix]`). One
        device dispatch per update() replaces the serialized path's host
        re-slice + re-upload (and re-dispatch) of every single minibatch."""
        import jax
        import optax

        def loss_fn(params, batch):
            loss, aux = self.compute_losses(params, batch)
            return loss, aux

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        @jax.jit
        def epochs(params, opt_state, batch, idx):
            def step(carry, ix):
                params, opt_state = carry
                mbatch = self._cast_obs({k: v[ix] for k, v in batch.items()})
                (loss, aux), grads = grad_fn(params, mbatch)
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), (loss, aux)

            (params, opt_state), (losses, auxs) = jax.lax.scan(
                step, (params, opt_state), idx)
            return params, opt_state, losses, auxs

        return epochs

    # -- collective group (multi-learner DDP analog) ---------------------------
    def setup_collective(self, group_name: str) -> None:
        self._group_name = group_name

    def _sync_grads(self, grads):
        if self._group_name is None:
            return grads
        import jax

        from ray_tpu.util import collective as col

        leaves, treedef = jax.tree_util.tree_flatten(grads)
        # graftlint: allow[host-sync-in-hot-path] host-plane shm allreduce: grads must land on host to ride the collective
        flat = np.concatenate([np.asarray(l).ravel() for l in leaves])
        reduced = col.allreduce(flat, group_name=self._group_name)
        reduced = reduced / col.get_collective_group_size(self._group_name)
        out, off = [], 0
        for l in leaves:
            n = int(np.prod(np.shape(l)))
            # graftlint: allow[host-sync-in-hot-path] reduced grads are host arrays by construction (shm backend)
            out.append(np.asarray(reduced[off : off + n]).reshape(np.shape(l)))
            off += n
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- update ---------------------------------------------------------------
    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """One pass of minibatch SGD epochs over the batch (learner.py:_update)."""
        n = len(batch[Columns.OBS])
        arrays = {k: v for k, v in batch.items()
                  if isinstance(v, np.ndarray) and len(v) == n}
        return self._minibatch_sgd(arrays, n)

    @hot_path(reason="the learner inner loop: one device dispatch per minibatch")
    def _minibatch_sgd(self, arrays: Dict[str, Any], n: int) -> Dict[str, Any]:
        """Minibatch SGD epochs over columns of length n (numpy or device).

        Default path uploads the batch to device ONCE and gathers each
        minibatch on device (`_gather_update_fn`); the legacy host-slicing
        path (re-slice + re-upload per minibatch) stays selectable via
        RAY_TPU_RL_HOST_SLICING for the `serialized_opt` bench row, and is
        still used by the multi-learner group path whose grad allreduce runs
        on host between the split halves of the step.
        """
        import jax

        mb = min(self.config.minibatch_size or n, n)
        epochs = self.config.num_epochs
        rng = np.random.default_rng(0)
        host_slicing = (self._group_name is not None
                        or os.environ.get("RAY_TPU_RL_HOST_SLICING", "0") == "1")
        if not host_slicing:
            arrays = {k: jax.device_put(v) for k, v in arrays.items()}
            # full minibatches only (constant shapes keep one jit trace),
            # every epoch's permutation stacked into one [steps, mb] matrix:
            # the whole SGD schedule is a single device dispatch
            idx = np.stack([
                rng.permutation(n)[: (n // mb) * mb].reshape(-1, mb)
                for _ in range(epochs)]).reshape(-1, mb).astype(np.int32)
            self.params, self.opt_state, losses, auxs = self._gather_update_fn(
                self.params, self.opt_state, arrays, idx)
            # ONE host sync for the whole update, after every minibatch ran
            self.metrics = {
                "total_loss": float(np.mean(np.asarray(losses))),  # graftlint: allow[host-sync-in-hot-path] single designed metrics fetch after the fused epoch scan
                **{k: float(np.asarray(v)[-1]) for k, v in auxs.items()},  # graftlint: allow[host-sync-in-hot-path] same designed metrics boundary
                "minibatch_steps": int(idx.shape[0]),
            }
            return self.metrics
        losses, aux_out = [], {}
        steps = 0
        arrays = {k: np.asarray(v) for k, v in arrays.items()}  # graftlint: allow[host-sync-in-hot-path] legacy/group path materializes the batch on host by design
        for _ in range(epochs):
            perm = rng.permutation(n)
            # full minibatches only: constant shapes keep one jit trace
            for start in range(0, n - mb + 1, mb):
                idx = perm[start : start + mb]
                if self._group_name is not None:
                    mbatch = {k: v[idx] for k, v in arrays.items()}
                    loss, aux, grads = self._update_fn(self.params, mbatch)
                    grads = self._sync_grads(grads)
                    updates, self.opt_state = self.optimizer.update(
                        grads, self.opt_state, self.params)
                    import optax

                    self.params = optax.apply_updates(self.params, updates)
                else:
                    mbatch = {k: v[idx] for k, v in arrays.items()}
                    self.params, self.opt_state, loss, aux = self._fused_update_fn(
                        self.params, self.opt_state, mbatch)
                losses.append(loss)
                aux_out = aux
                steps += 1
        # ONE host sync for the whole update, after every minibatch dispatched
        self.metrics = {
            "total_loss": float(np.mean([float(l) for l in losses])),  # graftlint: allow[host-sync-in-hot-path] single designed metrics fetch after all minibatches dispatched
            **{k: float(v) for k, v in aux_out.items()},  # graftlint: allow[host-sync-in-hot-path] same designed metrics boundary
            "minibatch_steps": steps,
        }
        return self.metrics

    # -- decoupled rollout-plane path ------------------------------------------
    def setup_decoupled(self, authkey: bytes, publisher: bool = False,
                        start_version: int = 0) -> None:
        """Join the rollout plane's zero-copy transport (block pulls in,
        versioned weight broadcasts out if this rank is the publisher).
        `start_version` preserves broadcast-version monotonicity when a
        restarted group re-attaches."""
        from ray_tpu.util.collective import ring

        self._plane = ring.get_plane(authkey, min_streams=2)
        self._is_publisher = bool(publisher)
        self._weights_version = int(start_version)

    def publish_weights(self) -> Tuple[int, Tuple[str, int], int]:
        """Publish current params as `rlwts:<version>` on this learner's data
        plane; keeps the previous version alive so a worker mid-pull never
        races a retract. Returns (version, addr, nbytes) for the mailbox."""
        from ..rollout_plane import pack_params

        self._weights_version += 1
        data = pack_params(self.params)
        self._plane.publish(f"rlwts:{self._weights_version}", data,
                            expected_read_bytes=0)
        stale = self._weights_version - 2
        if stale > 0:
            self._plane.retract(f"rlwts:{stale}")
        return (self._weights_version, tuple(self._plane.addr), len(data))

    def _build_prepare_fn(self):
        """Jitted block → train-batch transform: advantage pass ON DEVICE
        (gae_scan / V-trace over the block time axis) + masked batch-wide
        advantage standardization, replacing the host-numpy connector."""
        import jax
        import jax.numpy as jnp

        from ..utils.gae import gae_scan, vtrace_block

        cfg = self.config
        gamma = cfg.gamma
        lam = float(getattr(cfg, "lambda_", 0.95))
        correction = getattr(cfg, "correction", "is_clip")
        rho_thr = float(getattr(cfg, "vtrace_clip_rho_threshold", 1.0))
        pg_rho_thr = float(getattr(cfg, "vtrace_clip_pg_rho_threshold", 1.0))

        def standardize(adv, mask):
            msum = jnp.maximum(mask.sum(), 1.0)
            mean = (adv * mask).sum() / msum
            var = (((adv - mean) ** 2) * mask).sum() / msum
            return (adv - mean) / jnp.maximum(jnp.sqrt(var), 1e-6)

        if correction == "vtrace":

            @jax.jit
            def prepare(params, obs, actions, action_logp, rewards, vf_preds,
                        boot_values, terminated, truncated, valid):
                Tp1, B = obs.shape[0], obs.shape[1]
                T = Tp1 - 1
                # keep obs in the env's native dtype (uint8 frames stay
                # 1 B/px); the minibatch step casts its gathers (_cast_obs)
                obs_flat = obs.reshape(Tp1 * B, -1)
                term = terminated.astype(jnp.float32)
                trunc = truncated.astype(jnp.float32)
                mask = valid.astype(jnp.float32)
                rewards_f = rewards.astype(jnp.float32)
                out = self.module.forward_train(
                    params, {Columns.OBS: obs_flat.astype(jnp.float32)})
                values_ext = out[Columns.VF_PREDS].reshape(Tp1, B)
                dist = self.module.action_dist_cls
                logits = out[Columns.ACTION_DIST_INPUTS][: T * B]
                act_flat = actions.reshape((T * B,) + actions.shape[2:])
                target_logp = dist.logp_jax(logits, act_flat).reshape(T, B)
                rhos = jnp.exp(target_logp - action_logp) * mask
                adv, targets = vtrace_block(
                    rewards_f, values_ext[:T], values_ext[1:], term, trunc,
                    rhos, gamma=gamma, lambda_=1.0,
                    clip_rho_threshold=rho_thr,
                    clip_pg_rho_threshold=pg_rho_thr)
                adv = standardize(adv, mask)

                def flat(x):
                    return x.reshape((T * B,) + x.shape[2:])

                return {
                    Columns.OBS: obs_flat[: T * B],
                    Columns.ACTIONS: flat(actions),
                    Columns.ACTION_LOGP: flat(action_logp),
                    Columns.ADVANTAGES: flat(adv),
                    Columns.VALUE_TARGETS: flat(targets),
                    "loss_mask": flat(mask),
                }

            return prepare

        # "is_clip": GAE off behaviour values; PPO's ratio clip is the IS
        # correction. The advantage pass never touches obs, so the 50+ MB
        # obs block stays OUT of this program entirely — the caller attaches
        # it as a host view and the minibatch step uploads it once.
        @jax.jit
        def prepare(actions, action_logp, rewards, vf_preds,
                    boot_values, terminated, truncated, valid):
            T, B = actions.shape[0], actions.shape[1]
            term = terminated.astype(jnp.float32)
            trunc = truncated.astype(jnp.float32)
            mask = valid.astype(jnp.float32)
            rewards_f = rewards.astype(jnp.float32)
            adv, targets = gae_scan(
                rewards_f, vf_preds, boot_values, term, trunc,
                gamma=gamma, lambda_=lam)
            adv = standardize(adv, mask)

            def flat(x):
                return x.reshape((T * B,) + x.shape[2:])

            return {
                Columns.ACTIONS: flat(actions),
                Columns.ACTION_LOGP: flat(action_logp),
                Columns.ADVANTAGES: flat(adv),
                Columns.VALUE_TARGETS: flat(targets),
                "loss_mask": flat(mask),
            }

        return prepare

    def update_from_blocks(self, handles: List[Any]) -> Dict[str, Any]:
        """Decoupled update: land trajectory blocks (mapped adoption or
        striped pull), run the advantage pass inside the jitted program, and
        do minibatch SGD with on-device gathers. Returns metrics plus the
        fresh weights broadcast descriptor when this rank publishes."""
        from ..rollout_plane import read_block_arrays

        with telemetry.span("rl.learner_update", "rl", blocks=len(handles)):
            # single-block rounds adopt the mapped obs zero-copy; the pin is
            # released below once the SGD pass (whose end-of-update metrics
            # fetch synchronizes the device) has consumed it
            blocks = [read_block_arrays(h, self._plane, adopt=len(handles) == 1)
                      for h in handles]
            pins = [b.pop("_pin") for b in blocks if "_pin" in b]
            try:
                return self._update_from_fields(blocks, handles)
            finally:
                for p in pins:
                    p.release()

    def _update_from_fields(self, blocks, handles) -> Dict[str, Any]:
        if len(blocks) > 1:
            fields = {k: np.concatenate([b[k] for b in blocks], axis=1)
                      for k in blocks[0]}
        else:
            fields = blocks[0]
        if self._prepare_fn is None:
            self._prepare_fn = self._build_prepare_fn()
        if getattr(self.config, "correction", "is_clip") == "vtrace":
            batch = dict(self._prepare_fn(
                self.params, fields["obs"], fields["actions"],
                fields["action_logp"], fields["rewards"],
                fields["vf_preds"], fields["boot_values"],
                fields["terminated"], fields["truncated"],
                fields["valid"]))
        else:
            batch = dict(self._prepare_fn(
                fields["actions"], fields["action_logp"],
                fields["rewards"], fields["vf_preds"],
                fields["boot_values"], fields["terminated"],
                fields["truncated"], fields["valid"]))
            # native-dtype obs rides along as a zero-copy host VIEW of
            # the pinned block ([T*B] prefix); the minibatch step's
            # device_put uploads it once per update
            T, B = fields["actions"].shape[:2]
            batch[Columns.OBS] = fields["obs"][:T].reshape(T * B, -1)
        n = batch[Columns.ACTIONS].shape[0]
        metrics = self._minibatch_sgd(batch, n)
        telemetry.get_counter("rl_learner_updates_total").inc()
        metrics["env_steps"] = int(sum(h.env_steps for h in handles))
        return metrics

    # -- state ----------------------------------------------------------------
    def _host_params(self):
        import jax

        return jax.tree_util.tree_map(lambda a: np.asarray(a), self.params)

    def get_state(self) -> Dict[str, Any]:
        import jax

        return {"params": self._host_params(),
                "opt_state": jax.tree_util.tree_map(lambda a: np.asarray(a),
                                                    self.opt_state)}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        if state.get("opt_state") is not None:
            self.opt_state = state["opt_state"]

    def get_weights(self):
        return self._host_params()

    def ping(self) -> bool:
        return True
