"""LearnerGroup: N learner actors with synced gradients.

Capability parity: reference rllib/core/learner/learner_group.py:100 — sharded update
across learner actors; grad sync is a collective allreduce (see learner.py), the XLA
analog of the reference's torch-DDP NCCL ring.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

from .learner import Learner
from .rl_module import RLModuleSpec


class LearnerGroup:
    def __init__(
        self,
        config: "AlgorithmConfig",  # noqa: F821
        module_spec: RLModuleSpec,
        learner_class: type = Learner,
    ):
        self.config = config
        n = max(1, config.num_learners)
        self.n = n
        actor_cls = ray_tpu.remote(num_cpus=1, num_tpus=config.num_tpus_per_learner)(learner_class)
        self.learners = [actor_cls.remote(config, module_spec) for _ in range(n)]
        ray_tpu.get([l.build.remote() for l in self.learners])
        if n > 1:
            from ray_tpu.util import collective as col

            group = f"learner_group_{id(self):x}"
            # grad/weight sync payloads are model-sized: above the ring
            # threshold they move learner-to-learner over the data plane (the
            # coordinator actor carries metadata only); int8 wire compression
            # is the EQuARX-style opt-in for bandwidth-bound clusters
            col.create_collective_group(
                self.learners, n, list(range(n)), backend="shm", group_name=group,
                compression=getattr(config, "collective_compression", None))
            ray_tpu.get([l.setup_collective.remote(group) for l in self.learners])
            self._group = group
        else:
            self._group = None

    def update(self, batch: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Shard the batch across learners; each updates with allreduced grads.
        Accepts a flat column batch or (multi-agent) a module_id -> batch dict."""
        refs = []
        for i, learner in enumerate(self.learners):
            refs.append(learner.update.remote(self._shard(batch, i)))
        return ray_tpu.get(refs)

    def _shard(self, batch: Dict[str, Any], i: int) -> Dict[str, Any]:
        if batch and all(isinstance(v, dict) for v in batch.values()):
            return {mid: self._shard(sub, i) for mid, sub in batch.items()}
        n_rows = len(next(iter(batch.values())))
        per = n_rows // self.n
        return {k: v[i * per : (i + 1) * per] for k, v in batch.items() if isinstance(v, np.ndarray)}

    # -- decoupled rollout-plane path ------------------------------------------
    def setup_decoupled(self, authkey: bytes, start_version: int = 0) -> None:
        """Attach every learner to the rollout plane's data-plane transport;
        rank 0 becomes the weights publisher. `start_version` keeps the
        broadcast version monotonic across a restart-from-checkpoint."""
        ray_tpu.get([
            l.setup_decoupled.remote(authkey, i == 0, start_version)
            for i, l in enumerate(self.learners)
        ])

    def update_from_blocks(self, handles: List[Any]) -> List[Dict[str, Any]]:
        """Fan block handles out across learners (each pulls its own shard
        peer-to-peer from the announcing workers — payloads never route
        through the driver). With n>1 every learner must see the same block
        count so the grad-allreduce step counts line up; the caller provides
        len(handles) % n == 0 (BlockQueue.take is asked for a multiple)."""
        if self.n == 1:
            return [ray_tpu.get(
                self.learners[0].update_from_blocks.remote(handles))]
        per = len(handles) // self.n
        if per == 0:
            raise ValueError(
                f"need >= {self.n} blocks for {self.n} learners, got {len(handles)}")
        refs = [
            l.update_from_blocks.remote(handles[i * per:(i + 1) * per])
            for i, l in enumerate(self.learners)
        ]
        return ray_tpu.get(refs)

    def publish_weights(self):
        """Rank 0 publishes params on its data plane; returns the
        (version, addr, nbytes) broadcast descriptor for the block queue."""
        return ray_tpu.get(self.learners[0].publish_weights.remote())

    def get_weights(self):
        return ray_tpu.get(self.learners[0].get_weights.remote())

    def get_state(self) -> Dict[str, Any]:
        return ray_tpu.get(self.learners[0].get_state.remote())

    def set_state(self, state: Dict[str, Any]) -> None:
        ray_tpu.get([l.set_state.remote(state) for l in self.learners])

    def shutdown(self) -> None:
        for l in self.learners:
            try:
                ray_tpu.kill(l)
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass
