"""RLModule: the neural-network abstraction.

Capability parity: reference rllib/core/rl_module/rl_module.py — forward_inference /
forward_exploration / forward_train, get/set_state, inference-only view. JAX-first: a
module is a (init, apply) pair over a param pytree; the same pytree runs host-side
(numpy, env runners) and device-side (jax, learner) — no torch/DDP wrapping needed.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .distributions import Categorical, DiagGaussian

Columns = type("Columns", (), {
    "OBS": "obs",
    "ACTIONS": "actions",
    "REWARDS": "rewards",
    "TERMINATEDS": "terminateds",
    "TRUNCATEDS": "truncateds",
    "ACTION_DIST_INPUTS": "action_dist_inputs",
    "ACTION_LOGP": "action_logp",
    "VF_PREDS": "vf_preds",
    "ADVANTAGES": "advantages",
    "VALUE_TARGETS": "value_targets",
})


@dataclasses.dataclass
class RLModuleSpec:
    """Reference rl_module.py RLModuleSpec: how to build the module."""

    module_class: Optional[type] = None
    observation_space: Any = None
    action_space: Any = None
    model_config: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def build(self) -> "RLModule":
        cls = self.module_class or MLPModule
        return cls(self.observation_space, self.action_space, self.model_config)


class RLModule:
    """forward_* operate on dict batches and return dict outputs."""

    def __init__(self, observation_space, action_space, model_config: Dict[str, Any]):
        self.observation_space = observation_space
        self.action_space = action_space
        self.model_config = dict(model_config or {})

    # -- abstract -------------------------------------------------------------
    def init_params(self, seed: int = 0) -> Any:
        raise NotImplementedError

    def apply_jax(self, params: Any, obs) -> Dict[str, Any]:
        """Device-side forward (jax arrays in/out); used by the learner under jit."""
        raise NotImplementedError

    def apply_np(self, params: Any, obs: np.ndarray) -> Dict[str, np.ndarray]:
        """Host-side forward (numpy); used by env runners."""
        raise NotImplementedError

    @property
    def action_dist_cls(self):
        raise NotImplementedError

    # -- RLModule API shape ----------------------------------------------------
    def forward_inference(self, params, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = self.apply_np(params, batch[Columns.OBS])
        return out

    def forward_exploration(self, params, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        return self.apply_np(params, batch[Columns.OBS])

    def forward_train(self, params, batch: Dict[str, Any]) -> Dict[str, Any]:
        return self.apply_jax(params, batch[Columns.OBS])


def _mlp_init(rng: np.random.Generator, sizes) -> list:
    layers = []
    for fan_in, fan_out in zip(sizes[:-1], sizes[1:]):
        scale = np.sqrt(2.0 / fan_in)
        layers.append({
            "w": (rng.standard_normal((fan_in, fan_out)) * scale).astype(np.float32),
            "b": np.zeros((fan_out,), np.float32),
        })
    return layers


def _mlp_apply_np(layers, x: np.ndarray, final_linear: bool = True) -> np.ndarray:
    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1 or not final_linear:
            x = np.tanh(x)
    return x


def _mlp_apply_jax(layers, x, final_linear: bool = True):
    import jax.numpy as jnp

    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1 or not final_linear:
            x = jnp.tanh(x)
    return x


class MLPModule(RLModule):
    """Default policy+value MLP (reference catalog default: separate pi/vf trunks)."""

    def __init__(self, observation_space, action_space, model_config):
        super().__init__(observation_space, action_space, model_config)
        self.hiddens = tuple(model_config.get("fcnet_hiddens", (64, 64)))
        self.obs_dim = int(np.prod(observation_space.shape))
        import gymnasium as gym

        if isinstance(action_space, gym.spaces.Discrete):
            self.out_dim = int(action_space.n)
            self._dist_cls = Categorical
        else:
            self.act_dim = int(np.prod(action_space.shape))
            self.out_dim = 2 * self.act_dim
            self._dist_cls = DiagGaussian

    @property
    def action_dist_cls(self):
        return self._dist_cls

    def init_params(self, seed: int = 0):
        rng = np.random.default_rng(seed)
        pi = _mlp_init(rng, (self.obs_dim, *self.hiddens, self.out_dim))
        # near-zero final policy layer -> near-uniform initial policy
        pi[-1]["w"] *= 0.01
        vf = _mlp_init(rng, (self.obs_dim, *self.hiddens, 1))
        return {"pi": pi, "vf": vf}

    def apply_np(self, params, obs):
        obs = obs.reshape(len(obs), -1).astype(np.float32)
        logits = _mlp_apply_np(params["pi"], obs)
        vf = _mlp_apply_np(params["vf"], obs)[..., 0]
        return {Columns.ACTION_DIST_INPUTS: logits, Columns.VF_PREDS: vf}

    def apply_jax(self, params, obs):
        obs = obs.reshape(len(obs), -1)
        logits = _mlp_apply_jax(params["pi"], obs)
        vf = _mlp_apply_jax(params["vf"], obs)[..., 0]
        return {Columns.ACTION_DIST_INPUTS: logits, Columns.VF_PREDS: vf}


class SACModule(RLModule):
    """Squashed-Gaussian policy + twin Q critics for continuous control (SAC).

    Params: {"pi": mlp(obs -> 2A), "q1"/"q2": mlp([obs, act] -> 1),
    "log_alpha": scalar temperature (auto-tuned by the learner)}.
    """

    def __init__(self, observation_space, action_space, model_config):
        super().__init__(observation_space, action_space, model_config)
        import gymnasium as gym

        if not isinstance(action_space, gym.spaces.Box):
            raise ValueError("SACModule requires a Box action space")
        self.hiddens = tuple(model_config.get("fcnet_hiddens", (64, 64)))
        self.obs_dim = int(np.prod(observation_space.shape))
        self.act_dim = int(np.prod(action_space.shape))
        self.low = np.asarray(action_space.low, np.float32).reshape(-1)
        self.high = np.asarray(action_space.high, np.float32).reshape(-1)
        if not (np.isfinite(self.low).all() and np.isfinite(self.high).all()):
            raise ValueError(
                "SACModule requires finite action bounds (tanh squashing scales to "
                "[low, high]); wrap the env with a bounded Box action space")

    @property
    def action_dist_cls(self):
        from .distributions import SquashedGaussian

        return SquashedGaussian

    def init_params(self, seed: int = 0):
        rng = np.random.default_rng(seed)
        return {
            "pi": _mlp_init(rng, (self.obs_dim, *self.hiddens, 2 * self.act_dim)),
            "q1": _mlp_init(rng, (self.obs_dim + self.act_dim, *self.hiddens, 1)),
            "q2": _mlp_init(rng, (self.obs_dim + self.act_dim, *self.hiddens, 1)),
            "log_alpha": np.float32(0.0),
        }

    def _bounds_np(self, b):
        return (np.broadcast_to(self.low, (b, self.act_dim)),
                np.broadcast_to(self.high, (b, self.act_dim)))

    def apply_np(self, params, obs):
        obs = obs.reshape(len(obs), -1).astype(np.float32)
        out = _mlp_apply_np(params["pi"], obs)
        low, high = self._bounds_np(len(obs))
        return {
            Columns.ACTION_DIST_INPUTS: np.concatenate([out, low, high], axis=1),
            Columns.VF_PREDS: np.zeros(len(obs), np.float32),
        }

    def apply_jax(self, params, obs):
        import jax.numpy as jnp

        obs = obs.reshape(len(obs), -1)
        out = _mlp_apply_jax(params["pi"], obs)
        low = jnp.broadcast_to(jnp.asarray(self.low), (obs.shape[0], self.act_dim))
        high = jnp.broadcast_to(jnp.asarray(self.high), (obs.shape[0], self.act_dim))
        return {
            Columns.ACTION_DIST_INPUTS: jnp.concatenate([out, low, high], axis=1),
            Columns.VF_PREDS: jnp.zeros(obs.shape[0], jnp.float32),
        }

    # -- learner-side pieces -----------------------------------------------------
    def pi_jax(self, params, obs):
        """(mu, log_std) of the pre-squash Gaussian."""
        import jax.numpy as jnp

        from .distributions import LOG_STD_MAX, LOG_STD_MIN

        out = _mlp_apply_jax(params["pi"], obs.reshape(len(obs), -1))
        mu, log_std = out[..., : self.act_dim], out[..., self.act_dim:]
        return mu, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)

    def sample_action_jax(self, params, obs, rng):
        """Reparameterized squashed sample + its log-prob (for actor/critic losses)."""
        import jax
        import jax.numpy as jnp

        from .distributions import squashed_logp_from_u_jax

        mu, log_std = self.pi_jax(params, obs)
        std = jnp.exp(log_std)
        u = mu + std * jax.random.normal(rng, mu.shape)
        t = jnp.tanh(u)
        low, high = jnp.asarray(self.low), jnp.asarray(self.high)
        action = low + (t + 1.0) * 0.5 * (high - low)
        logp = squashed_logp_from_u_jax(u, t, mu, log_std, low, high)
        return action, logp

    def q_jax(self, params, which, obs, actions):
        import jax.numpy as jnp

        x = jnp.concatenate([obs.reshape(len(obs), -1), actions], axis=-1)
        return _mlp_apply_jax(params[which], x)[..., 0]


class DQNModule(RLModule):
    """Q-network for discrete actions (reference dqn_rainbow_rl_module).

    Params carry a non-trained "epsilon" leaf: its task-loss gradient is exactly
    zero (loss never reads it), so the optimizer leaves it alone, and the DQN
    algorithm overwrites it per the schedule before syncing weights to runners —
    exploration state rides the ordinary weight-sync path."""

    def __init__(self, observation_space, action_space, model_config):
        super().__init__(observation_space, action_space, model_config)
        import gymnasium as gym

        if not isinstance(action_space, gym.spaces.Discrete):
            raise ValueError("DQNModule requires a Discrete action space")
        self.hiddens = tuple(model_config.get("fcnet_hiddens", (64, 64)))
        self.obs_dim = int(np.prod(observation_space.shape))
        self.num_actions = int(action_space.n)

    @property
    def action_dist_cls(self):
        from .distributions import EpsilonGreedyQ

        return EpsilonGreedyQ

    def init_params(self, seed: int = 0):
        rng = np.random.default_rng(seed)
        q = _mlp_init(rng, (self.obs_dim, *self.hiddens, self.num_actions))
        return {"q": q, "epsilon": np.float32(1.0)}

    def q_values_np(self, params, obs: np.ndarray) -> np.ndarray:
        obs = obs.reshape(len(obs), -1).astype(np.float32)
        return _mlp_apply_np(params["q"], obs)

    def q_values_jax(self, params, obs):
        obs = obs.reshape(len(obs), -1)
        return _mlp_apply_jax(params["q"], obs)

    def apply_np(self, params, obs):
        q = self.q_values_np(params, obs)
        eps = np.full((len(q), 1), float(params["epsilon"]), np.float32)
        return {
            Columns.ACTION_DIST_INPUTS: np.concatenate([q, eps], axis=1),
            Columns.VF_PREDS: q.max(axis=-1),
        }

    def apply_jax(self, params, obs):
        import jax.numpy as jnp

        q = self.q_values_jax(params, obs)
        eps = jnp.full((q.shape[0], 1), params["epsilon"])
        return {
            Columns.ACTION_DIST_INPUTS: jnp.concatenate([q, eps], axis=1),
            Columns.VF_PREDS: q.max(axis=-1),
        }
