"""Action distributions (reference rllib/models/distributions.py, torch/jax-agnostic).

Pure numpy/jax implementations: logits come from the RLModule; sampling happens host-side
in env runners (numpy) and log-prob/entropy gradients device-side in the learner (jax).
"""
from __future__ import annotations

from typing import Any

import numpy as np


class Distribution:
    @staticmethod
    def sample_np(dist_inputs: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        raise NotImplementedError

    @staticmethod
    def logp_np(dist_inputs: np.ndarray, actions: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    @staticmethod
    def logp_jax(dist_inputs, actions):
        raise NotImplementedError

    @staticmethod
    def entropy_jax(dist_inputs):
        raise NotImplementedError


class Categorical(Distribution):
    """Discrete actions; dist_inputs = logits [B, n]."""

    @staticmethod
    def sample_np(logits: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        z = logits - logits.max(axis=-1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(axis=-1, keepdims=True)
        cum = np.cumsum(p, axis=-1)
        r = rng.random(size=(len(p), 1))
        # float32 cum[-1] can be slightly < 1.0; clamp so r in the tail stays in range
        return np.minimum((r > cum).sum(axis=-1), p.shape[-1] - 1).astype(np.int64)

    @staticmethod
    def logp_np(logits: np.ndarray, actions: np.ndarray) -> np.ndarray:
        z = logits - logits.max(axis=-1, keepdims=True)
        logz = np.log(np.exp(z).sum(axis=-1))
        return z[np.arange(len(z)), actions.astype(np.int64)] - logz

    @staticmethod
    def logp_jax(logits, actions):
        import jax.numpy as jnp
        from jax.nn import log_softmax

        lp = log_softmax(logits, axis=-1)
        return jnp.take_along_axis(lp, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]

    @staticmethod
    def entropy_jax(logits):
        import jax.numpy as jnp
        from jax.nn import log_softmax, softmax

        lp = log_softmax(logits, axis=-1)
        return -jnp.sum(softmax(logits, axis=-1) * lp, axis=-1)

    @staticmethod
    def greedy_np(logits: np.ndarray) -> np.ndarray:
        return logits.argmax(axis=-1)


LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0
# numeric guards for the tanh change-of-variables (shared by every squashed-logp path)
TANH_CLIP = 0.999999
SQUASH_EPS = 1e-9


def squashed_logp_from_u_jax(u, t, mu, log_std, low, high):
    """log p(a) for a = low + (tanh(u)+1)/2*(high-low), u ~ N(mu, exp(log_std)).

    THE single jax implementation of the tanh-Gaussian change of variables —
    used by SquashedGaussian.logp_jax and SACModule.sample_action_jax so the
    env-runner, learner, and reparameterized-actor log-probs cannot drift.
    """
    import jax.numpy as jnp

    std = jnp.exp(log_std)
    span = (high - low) * 0.5
    base = -0.5 * (((u - mu) / std) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
    corr = jnp.log(jnp.maximum(span * (1 - t**2), SQUASH_EPS))
    return (base - corr).sum(-1)


class SquashedGaussian(Distribution):
    """tanh-squashed diagonal Gaussian scaled to the action bounds (SAC).

    dist_inputs: [B, 4A] — mu, log_std, low, high (bounds ride the inputs the
    same way EpsilonGreedyQ carries epsilon, keeping the distribution stateless).
    """

    @staticmethod
    def _split(x):
        a = x.shape[-1] // 4
        return x[..., :a], np.clip(x[..., a:2 * a], LOG_STD_MIN, LOG_STD_MAX), \
            x[..., 2 * a:3 * a], x[..., 3 * a:]

    @staticmethod
    def _scale(u, low, high):
        return low + (np.tanh(u) + 1.0) * 0.5 * (high - low)

    @staticmethod
    def sample_np(inputs: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        mu, log_std, low, high = SquashedGaussian._split(inputs)
        u = mu + np.exp(log_std) * rng.standard_normal(mu.shape)
        return SquashedGaussian._scale(u, low, high).astype(np.float32)

    @staticmethod
    def greedy_np(inputs: np.ndarray) -> np.ndarray:
        mu, _, low, high = SquashedGaussian._split(inputs)
        return SquashedGaussian._scale(mu, low, high).astype(np.float32)

    @staticmethod
    def logp_np(inputs: np.ndarray, actions: np.ndarray) -> np.ndarray:
        mu, log_std, low, high = SquashedGaussian._split(inputs)
        span = (high - low) * 0.5
        t = np.clip((actions - low) / np.maximum(high - low, 1e-9) * 2 - 1,
                    -0.999999, 0.999999)
        u = np.arctanh(t)
        std = np.exp(log_std)
        base = -0.5 * (((u - mu) / std) ** 2 + 2 * log_std + np.log(2 * np.pi))
        # change of variables: da = span * (1 - tanh(u)^2) du
        corr = np.log(np.maximum(span * (1 - t**2), 1e-9))
        return (base - corr).sum(-1).astype(np.float32)

    @staticmethod
    def logp_jax(inputs, actions):
        import jax.numpy as jnp

        a = inputs.shape[-1] // 4
        mu, log_std = inputs[..., :a], jnp.clip(inputs[..., a:2 * a],
                                                LOG_STD_MIN, LOG_STD_MAX)
        low, high = inputs[..., 2 * a:3 * a], inputs[..., 3 * a:]
        t = jnp.clip((actions - low) / jnp.maximum(high - low, SQUASH_EPS) * 2 - 1,
                     -TANH_CLIP, TANH_CLIP)
        u = jnp.arctanh(t)
        return squashed_logp_from_u_jax(u, t, mu, log_std, low, high)

    @staticmethod
    def entropy_jax(inputs):
        import jax.numpy as jnp

        a = inputs.shape[-1] // 4
        log_std = jnp.clip(inputs[..., a:2 * a], LOG_STD_MIN, LOG_STD_MAX)
        # pre-squash gaussian entropy (the squash correction has no closed form)
        return (log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e)).sum(-1)


class EpsilonGreedyQ(Distribution):
    """Epsilon-greedy over Q-values (DQN exploration).

    dist_inputs: [B, A+1] — Q-values with the CURRENT epsilon appended as the
    last column (the module owns epsilon as a non-trained parameter so the
    schedule rides the normal weight-sync path to env runners)."""

    @staticmethod
    def sample_np(inputs: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        q, eps = inputs[:, :-1], float(inputs[0, -1])
        greedy = q.argmax(axis=-1)
        rand = rng.integers(0, q.shape[1], size=len(q))
        take_rand = rng.random(len(q)) < eps
        return np.where(take_rand, rand, greedy)

    @staticmethod
    def greedy_np(inputs: np.ndarray) -> np.ndarray:
        return inputs[:, :-1].argmax(axis=-1)

    @staticmethod
    def logp_np(inputs: np.ndarray, actions: np.ndarray) -> np.ndarray:
        return np.zeros(len(actions), np.float32)  # DQN losses never use logp

    @staticmethod
    def logp_jax(inputs, actions):
        import jax.numpy as jnp

        return jnp.zeros(inputs.shape[0], jnp.float32)

    @staticmethod
    def entropy_jax(inputs):
        import jax.numpy as jnp

        return jnp.zeros(inputs.shape[0], jnp.float32)


class DiagGaussian(Distribution):
    """Continuous actions; dist_inputs = [mean, log_std] concat on last dim [B, 2*d]."""

    @staticmethod
    def _split(x):
        d = x.shape[-1] // 2
        return x[..., :d], x[..., d:]

    @staticmethod
    def sample_np(inputs: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        mean, log_std = DiagGaussian._split(inputs)
        return mean + np.exp(log_std) * rng.standard_normal(mean.shape)

    @staticmethod
    def logp_np(inputs: np.ndarray, actions: np.ndarray) -> np.ndarray:
        mean, log_std = DiagGaussian._split(inputs)
        var = np.exp(2 * log_std)
        return (-0.5 * ((actions - mean) ** 2 / var + 2 * log_std + np.log(2 * np.pi))).sum(-1)

    @staticmethod
    def logp_jax(inputs, actions):
        import jax.numpy as jnp

        d = inputs.shape[-1] // 2
        mean, log_std = inputs[..., :d], inputs[..., d:]
        var = jnp.exp(2 * log_std)
        return (-0.5 * ((actions - mean) ** 2 / var + 2 * log_std + jnp.log(2 * jnp.pi))).sum(-1)

    @staticmethod
    def entropy_jax(inputs):
        import jax.numpy as jnp

        d = inputs.shape[-1] // 2
        log_std = inputs[..., d:]
        return (log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e)).sum(-1)

    @staticmethod
    def greedy_np(inputs: np.ndarray) -> np.ndarray:
        mean, _ = DiagGaussian._split(inputs)
        return mean
