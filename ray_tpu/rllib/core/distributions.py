"""Action distributions (reference rllib/models/distributions.py, torch/jax-agnostic).

Pure numpy/jax implementations: logits come from the RLModule; sampling happens host-side
in env runners (numpy) and log-prob/entropy gradients device-side in the learner (jax).
"""
from __future__ import annotations

from typing import Any

import numpy as np


class Distribution:
    @staticmethod
    def sample_np(dist_inputs: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        raise NotImplementedError

    @staticmethod
    def logp_np(dist_inputs: np.ndarray, actions: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    @staticmethod
    def logp_jax(dist_inputs, actions):
        raise NotImplementedError

    @staticmethod
    def entropy_jax(dist_inputs):
        raise NotImplementedError


class Categorical(Distribution):
    """Discrete actions; dist_inputs = logits [B, n]."""

    @staticmethod
    def sample_np(logits: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        z = logits - logits.max(axis=-1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(axis=-1, keepdims=True)
        cum = np.cumsum(p, axis=-1)
        r = rng.random(size=(len(p), 1))
        # float32 cum[-1] can be slightly < 1.0; clamp so r in the tail stays in range
        return np.minimum((r > cum).sum(axis=-1), p.shape[-1] - 1).astype(np.int64)

    @staticmethod
    def logp_np(logits: np.ndarray, actions: np.ndarray) -> np.ndarray:
        z = logits - logits.max(axis=-1, keepdims=True)
        logz = np.log(np.exp(z).sum(axis=-1))
        return z[np.arange(len(z)), actions.astype(np.int64)] - logz

    @staticmethod
    def logp_jax(logits, actions):
        import jax.numpy as jnp
        from jax.nn import log_softmax

        lp = log_softmax(logits, axis=-1)
        return jnp.take_along_axis(lp, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]

    @staticmethod
    def entropy_jax(logits):
        import jax.numpy as jnp
        from jax.nn import log_softmax, softmax

        lp = log_softmax(logits, axis=-1)
        return -jnp.sum(softmax(logits, axis=-1) * lp, axis=-1)

    @staticmethod
    def greedy_np(logits: np.ndarray) -> np.ndarray:
        return logits.argmax(axis=-1)


class EpsilonGreedyQ(Distribution):
    """Epsilon-greedy over Q-values (DQN exploration).

    dist_inputs: [B, A+1] — Q-values with the CURRENT epsilon appended as the
    last column (the module owns epsilon as a non-trained parameter so the
    schedule rides the normal weight-sync path to env runners)."""

    @staticmethod
    def sample_np(inputs: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        q, eps = inputs[:, :-1], float(inputs[0, -1])
        greedy = q.argmax(axis=-1)
        rand = rng.integers(0, q.shape[1], size=len(q))
        take_rand = rng.random(len(q)) < eps
        return np.where(take_rand, rand, greedy)

    @staticmethod
    def greedy_np(inputs: np.ndarray) -> np.ndarray:
        return inputs[:, :-1].argmax(axis=-1)

    @staticmethod
    def logp_np(inputs: np.ndarray, actions: np.ndarray) -> np.ndarray:
        return np.zeros(len(actions), np.float32)  # DQN losses never use logp

    @staticmethod
    def logp_jax(inputs, actions):
        import jax.numpy as jnp

        return jnp.zeros(inputs.shape[0], jnp.float32)

    @staticmethod
    def entropy_jax(inputs):
        import jax.numpy as jnp

        return jnp.zeros(inputs.shape[0], jnp.float32)


class DiagGaussian(Distribution):
    """Continuous actions; dist_inputs = [mean, log_std] concat on last dim [B, 2*d]."""

    @staticmethod
    def _split(x):
        d = x.shape[-1] // 2
        return x[..., :d], x[..., d:]

    @staticmethod
    def sample_np(inputs: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        mean, log_std = DiagGaussian._split(inputs)
        return mean + np.exp(log_std) * rng.standard_normal(mean.shape)

    @staticmethod
    def logp_np(inputs: np.ndarray, actions: np.ndarray) -> np.ndarray:
        mean, log_std = DiagGaussian._split(inputs)
        var = np.exp(2 * log_std)
        return (-0.5 * ((actions - mean) ** 2 / var + 2 * log_std + np.log(2 * np.pi))).sum(-1)

    @staticmethod
    def logp_jax(inputs, actions):
        import jax.numpy as jnp

        d = inputs.shape[-1] // 2
        mean, log_std = inputs[..., :d], inputs[..., d:]
        var = jnp.exp(2 * log_std)
        return (-0.5 * ((actions - mean) ** 2 / var + 2 * log_std + jnp.log(2 * jnp.pi))).sum(-1)

    @staticmethod
    def entropy_jax(inputs):
        import jax.numpy as jnp

        d = inputs.shape[-1] // 2
        log_std = inputs[..., d:]
        return (log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e)).sum(-1)

    @staticmethod
    def greedy_np(inputs: np.ndarray) -> np.ndarray:
        mean, _ = DiagGaussian._split(inputs)
        return mean
