"""ray-tpu CLI: start/stop/status/submit/job (reference python/ray/scripts/
scripts.py — `ray start` :676, `ray submit` :1718, `ray stop` :1184, plus the
`ray job` group from dashboard/modules/job/cli.py).

Single-host note: the runtime is in-process (no separate GCS/raylet daemons), so
`start` records the head session + brings up the dashboard for external
observation, and drivers attach by just calling ray_tpu.init() — the reference's
`ray.init(address=...)` flow collapses to session-dir discovery.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

from ray_tpu.job.manager import JobManager, default_session_dir


def _session_file() -> str:
    return os.path.join(default_session_dir(), "head.json")


def cmd_start(args) -> int:
    if args.address:
        # join an existing head as this host's node agent (reference:
        # `ray start --address=...` bringing up a worker-node raylet)
        from ray_tpu.core.node_agent import agent_main

        resources = None
        if args.num_cpus is not None:
            from ray_tpu.core.resources import normalize_resources

            resources = normalize_resources(num_cpus=args.num_cpus, num_tpus=0.0,
                                            resources=None)
        print(f"joining head at {args.address} as a node agent (ctrl-c to leave)")
        try:
            agent_main(args.address, resources=resources)
        except KeyboardInterrupt:
            pass
        return 0
    os.makedirs(default_session_dir(), exist_ok=True)
    from ray_tpu.config import CONFIG

    dashboard_port = (args.dashboard_port if args.dashboard_port is not None
                      else CONFIG.dashboard_port)
    info = {
        "started_at": time.time(),
        "pid": os.getpid(),
        "num_cpus": args.num_cpus,
        "dashboard_port": dashboard_port,
    }
    if args.node_server_port is not None:
        info["node_server_port"] = args.node_server_port
    with open(_session_file(), "w") as f:
        json.dump(info, f)
    print(f"ray_tpu head session recorded at {_session_file()}")
    if args.block:
        import ray_tpu
        from ray_tpu.dashboard import Dashboard

        ray_tpu.init(num_cpus=args.num_cpus,
                     node_server_port=args.node_server_port,
                     node_server_host=args.node_server_host)
        if args.node_server_port is not None:
            from ray_tpu.core import global_state

            port = global_state.cluster().node_server_port
            print(f"node server: {args.node_server_host}:{port} "
                  "(join with `ray-tpu start --address=HOST:PORT`)")
        dash = Dashboard(port=dashboard_port)
        scheme = "https" if CONFIG.serve_ingress_tls else "http"
        print(f"dashboard: {scheme}://127.0.0.1:{dashboard_port}/api/summary")
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            dash.stop()
            ray_tpu.shutdown()
    return 0


def cmd_tls_init(args) -> int:
    from ray_tpu.core.tls_utils import generate_self_signed_tls

    paths = generate_self_signed_tls(args.dir, extra_sans=tuple(args.san))
    print("wrote:")
    for name, p in paths.items():
        print(f"  {name}: {p}")
    print("enable with:")
    print("  export RAY_TPU_USE_TLS=1")
    print(f"  export RAY_TPU_TLS_CA={paths['ca']}")
    print(f"  export RAY_TPU_TLS_CERT={paths['cert']}")
    print(f"  export RAY_TPU_TLS_KEY={paths['key']}")
    print("WARNING: keep the CA private key OFF cluster nodes — distribute only "
          "ca.crt, cluster.crt and cluster.key; anyone holding "
          f"{paths['ca_key']} can mint certificates this cluster trusts.")
    return 0


def cmd_stop(args) -> int:
    try:
        os.remove(_session_file())
        print("head session cleared")
    except FileNotFoundError:
        print("no head session")
    return 0


def _render_status(s: dict) -> str:
    """Human-facing render of util/state.cluster_status(): one short block per
    subsystem, omitting rows with no signal yet."""
    lines = []
    c = s.get("cluster", {})
    lines.append(f"cluster    nodes={c.get('nodes')} workers={c.get('workers')} "
                 f"actors={c.get('actors')} pending_tasks={c.get('pending_tasks')}")
    tr = s.get("transfer", {})
    for path, row in sorted(tr.items()):
        gbps = f"{row['gbps']:.2f} GB/s" if row.get("gbps") is not None else "-"
        lines.append(f"transfer   [{path}] pulls={row['pulls']} "
                     f"bytes={row['bytes']:,} rate={gbps}")
    col = s.get("collective", {})
    if col.get("ops") or col.get("aborts"):
        ops = " ".join(f"{k}={v}" for k, v in sorted(col.get("ops", {}).items()))
        lines.append(f"collective ops: {ops or '-'}  aborts={col.get('aborts', 0)} "
                     f"observed={col.get('aborts_observed', 0)} "
                     f"epoch_rollovers={col.get('epoch_rollovers', 0)}")
    sv = s.get("serve", {})
    if sv.get("requests") or sv.get("queue_depth"):
        def ms(v):
            return f"{v * 1e3:.1f}ms" if v is not None else "-"

        depth = " ".join(f"{k}:{int(v)}" for k, v in sorted(
            sv.get("queue_depth", {}).items()))
        lines.append(f"serve      requests={sv.get('requests', 0)} "
                     f"ttft_p50={ms(sv.get('ttft_p50_s'))} "
                     f"ttft_p99={ms(sv.get('ttft_p99_s'))} "
                     f"queue_depth[{depth or '-'}]")
    asc = sv.get("autoscale") or {}
    if asc.get("targets") or asc.get("decisions_by_reason"):
        for key, row in sorted(asc.get("targets", {}).items()):
            burn = "burning" if row.get("burning") else "ok"
            lines.append(
                f"autoscale  {key}: target={row.get('target')} "
                f"running={row.get('running')} "
                f"queue={row.get('queue_depth', 0):.0f} {burn} "
                f"({row.get('reason', '-')})")
        last = asc.get("last_decision")
        reasons = " ".join(f"{k}={v}" for k, v in sorted(
            asc.get("decisions_by_reason", {}).items()))
        tail = f"  last={last.get('event')}:{last.get('reason', '')}" \
            if isinstance(last, dict) and last.get("event") != "scale" else ""
        if last and isinstance(last, dict) and last.get("event") == "scale":
            tail = (f"  last={last['key']} {last['from']}->{last['to']} "
                    f"({last['reason']})")
        lines.append(f"autoscale  decisions[{reasons or '-'}]{tail}")
    llm = s.get("llm", {})
    if llm.get("prefix_cache_hits") or llm.get("active") or llm.get("pending"):
        fused = " ".join(f"{k}:{int(v)}" for k, v in sorted(
            (llm.get("fused_steps") or {}).items()))
        burst = llm.get("burst_tokens_per_s_p50")
        burst_txt = f"{burst:.0f}" if burst else "-"
        lines.append(f"llm        pending={llm.get('pending')} "
                     f"active={llm.get('active')} "
                     f"tokens={llm.get('generated_tokens', 0)} "
                     f"burst_tok/s_p50={burst_txt} "
                     f"fused_k[{fused or '-'}] "
                     f"prefix_cache hit/miss/skip="
                     f"{llm.get('prefix_cache_hits', 0)}/"
                     f"{llm.get('prefix_cache_misses', 0)}/"
                     f"{llm.get('prefix_cache_skipped', 0)}")
    cp = s.get("control_plane", {})
    if cp.get("scrape_p99_s") is not None or cp.get("nodes_aggregated"):
        def cms(v):
            return f"{v * 1e3:.1f}ms" if v is not None else "-"

        dec = " ".join(f"{k}:{cms(v)}" for k, v in sorted(
            (cp.get("decision_p99_s") or {}).items()))
        lines.append(f"control    scrape_p99={cms(cp.get('scrape_p99_s'))} "
                     f"decision_p99[{dec or '-'}] "
                     f"agg_nodes={cp.get('nodes_aggregated', 0)} "
                     f"direct_workers={cp.get('workers_direct', 0)}")
        dropped = sum((cp.get("dropped_series") or {}).values())
        if (cp.get("backpressure_level") or cp.get("inlet_shed")
                or cp.get("backpressure_transitions") or dropped):
            lines.append(
                f"control    backpressure level={cp.get('backpressure_level', 0) or 0:.0f} "
                f"transitions={cp.get('backpressure_transitions', 0)} "
                f"inlet_frames={cp.get('inlet_frames') or 0:.0f} "
                f"shed={cp.get('inlet_shed', 0)} dropped_series={dropped}")
    tn = s.get("train", {})
    if tn.get("mfu") or tn.get("step_phases_s"):
        mfu = " ".join(f"{k}:{v:.3f}" for k, v in sorted(tn.get("mfu", {}).items()))
        phases = " ".join(f"{k}:{v * 1e3:.1f}ms"
                          for k, v in sorted(tn.get("step_phases_s", {}).items()))
        lines.append(f"train      mfu[{mfu or '-'}] step_phases[{phases or '-'}]")
    bubbles = tn.get("pipeline_bubble_fraction") or {}
    if bubbles:
        frac = " ".join(f"{k}:{v:.2f}" for k, v in sorted(bubbles.items()))
        lines.append(f"train      pipeline_bubble[{frac}]")
    rl = s.get("rl", {})
    if rl.get("env_steps") or rl.get("learner_updates"):
        blocks = " ".join(f"{k}:{v}" for k, v in sorted(
            (rl.get("blocks") or {}).items()))
        lag99 = rl.get("block_lag_p99")
        lines.append(f"rl         env_steps={rl.get('env_steps', 0)} "
                     f"updates={rl.get('learner_updates', 0)} "
                     f"broadcasts={rl.get('weight_broadcasts', 0)} "
                     f"blocks[{blocks or '-'}] "
                     f"queue_depth={rl.get('queue_depth') or 0:.0f} "
                     f"lag_p99={f'{lag99:.1f}' if lag99 is not None else '-'}")
    return "\n".join(lines)


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _sparkline(values, width: int = 30) -> str:
    """Render a numeric series (None = no data) as unicode block bars.
    Scaled against the RENDERED slice only — an old spike outside the last
    `width` points must not flatten every visible bar."""
    values = values[-width:]
    vals = [v for v in values if v is not None]
    if not vals:
        return "-" * min(width, 8)
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    out = []
    for v in values:
        if v is None:
            out.append(" ")
        else:
            out.append(_SPARK_BLOCKS[min(7, int((v - lo) / span * 7.999))])
    return "".join(out)


def _render_history(hist: dict) -> str:
    """Sparkline block for `ray-tpu status --watch`: one row per history
    series that has any signal, latest value alongside."""
    ts, series = hist.get("ts", []), hist.get("series", {})
    if len(ts) < 2:
        return "history    (warming up: <2 frames scraped yet)"
    lines = []
    for name, vals in series.items():
        live = [v for v in vals if v is not None]
        if not live:
            continue
        latest = live[-1]
        if name.endswith("_per_s"):
            shown = f"{latest:,.1f}/s"
        elif name.endswith("_s"):
            shown = f"{latest * 1e3:.1f}ms"
        else:
            shown = f"{latest:,.1f}"
        lines.append(f"  {name:<24} {_sparkline(vals)} {shown}")
    if not lines:
        return "history    (no series with data yet)"
    span = ts[-1] - ts[0]
    return "\n".join([f"history    last {span:.0f}s, {len(ts)} frames:"] + lines)


def _render_slo(status: dict) -> str:
    if not status:
        return ""
    lines = ["slo"]
    for name, row in sorted(status.items()):
        state = row.get("state", "?")
        mark = {"ok": "·", "burning": "!", "no_data": "?"}.get(state, "?")
        bl, bs = row.get("burn_rate_long"), row.get("burn_rate_short")
        fmt = lambda b: f"{b:.2f}" if b is not None else "-"
        lines.append(f"  [{mark}] {name:<16} {state:<8} "
                     f"burn long/short={fmt(bl)}/{fmt(bs)} "
                     f"(objective {row.get('objective')}, "
                     f"window {row.get('window_s')}s)")
    return "\n".join(lines)


def cmd_status(args) -> int:
    """Head-session info plus — when a cluster is reachable (in-process or via
    --address) — the live telemetry summary: per-path transfer GB/s,
    collective ops/aborts, serve TTFT p50/p99 + queue depths, train MFU.
    --watch re-renders every few seconds with metrics-history sparklines and
    SLO burn state."""
    import ray_tpu

    rc = 0
    try:
        with open(_session_file()) as f:
            info = json.load(f)
        print(json.dumps(info, indent=2))
    except FileNotFoundError:
        print("no head session; run `ray-tpu start`")
        rc = 1
    if getattr(args, "address", None):
        try:
            ray_tpu.init(address=args.address)
        except Exception as e:  # noqa: BLE001 — keep the session-info contract
            print(f"(could not reach {args.address}: {e!r})", file=sys.stderr)
    if ray_tpu.is_initialized():
        from ray_tpu.util import state as rs

        if getattr(args, "watch", False):
            try:
                while True:
                    block = [_render_status(rs.cluster_status()),
                             _render_history(rs.history_series())]
                    slo = _render_slo(rs.slo_status())
                    if slo:
                        block.append(slo)
                    print("\x1b[2J\x1b[H" + "\n".join(block), flush=True)
                    time.sleep(args.interval)
            except KeyboardInterrupt:
                return rc
        print(_render_status(rs.cluster_status()))
        slo = _render_slo(rs.slo_status())
        if slo:
            print(slo)
    else:
        # stderr: standalone `ray-tpu status` must keep stdout pure JSON for
        # scripts that parse the session info
        print("(no live cluster for a load summary: pass --address "
              "ray-tpu://host:port or run inside a driver)", file=sys.stderr)
    # rc reflects the head session (the original `status` contract) — a live
    # in-process cluster adds the load summary but doesn't fake a session
    return rc


def cmd_trace(args) -> int:
    """`ray-tpu trace <trace_id>`: render one request's critical path — the
    cross-process span tree plus wall-time attribution over queue / prefill /
    decode / transfer / other. The trace id comes from the serve ingress's
    `traceparent` response header (or the caller's own traceparent)."""
    import ray_tpu

    if args.address:
        ray_tpu.init(address=args.address)
    elif not ray_tpu.is_initialized():
        print("no cluster: pass --address ray-tpu://host:port (or run inside a driver)")
        return 1
    from ray_tpu.util import state as rs

    doc = rs.request_trace(args.trace_id)
    if args.json:
        print(json.dumps(doc, indent=2, default=str))
        return 0 if doc.get("found") else 1
    if not doc.get("found"):
        print(f"no spans or events for trace {args.trace_id!r} (is tracing "
              "enabled, and did the request finish?)")
        return 1
    total = doc["total_s"]
    print(f"trace {doc['trace_id']}  total={total * 1e3:.1f}ms  "
          f"processes={len(doc['processes'])} ({', '.join(doc['processes'])})")
    print("spans:")
    for s in doc["spans"]:
        bar = "  " * s["depth"]
        print(f"  {bar}{s['name']}  +{s['start_s'] * 1e3:.1f}ms "
              f"{s['dur_s'] * 1e3:.1f}ms  (pid {s['pid']})")
    if doc["events"]:
        print("events:")
        for e in doc["events"]:
            phase = f" [{e['phase']}]" if e.get("phase") else ""
            print(f"  {e['name']}{phase}  +{e['start_s'] * 1e3:.1f}ms "
                  f"{e['dur_s'] * 1e3:.1f}ms  ({e['proc']})")
    print("critical path:")
    for phase, secs in doc["attribution"].items():
        pct = secs / total * 100 if total > 0 else 0.0
        if secs > 0 or phase == "other":
            print(f"  {phase:<9} {secs * 1e3:8.1f}ms  {pct:5.1f}%")
    return 0


def cmd_lint(args) -> int:
    """`ray-tpu lint [paths] [--write-docs]`: graftlint, the project-invariant
    static analyzer (ray_tpu/tools/analysis). Pure AST — no jax, no cluster.
    `--write-docs` regenerates the README knob tables from ray_tpu/knobs.py."""
    from ray_tpu.tools.analysis.runner import main as lint_main

    forwarded = list(args.lint_args)
    if args.write_docs:
        forwarded.append("--write-docs")
    if args.json:
        forwarded.append("--json")
    if args.show_allowed:
        forwarded.append("--show-allowed")
    return lint_main(forwarded)


def cmd_submit(args) -> int:
    mgr = JobManager()
    entry = " ".join([sys.executable, args.script] + args.script_args)
    job_id = mgr.submit_job(entry)
    print(f"submitted {job_id}")
    status = mgr.wait_job(job_id)
    print(mgr.get_job_logs(job_id), end="")
    print(f"job {job_id}: {status}")
    return 0 if status == "SUCCEEDED" else 1


def cmd_serve(args) -> int:
    """serve deploy/status/shutdown (reference serve CLI over ServeDeploySchema).

    Single-host note: the runtime is in-process, so the serving cluster lives in
    THIS process — deploy therefore blocks (apps would vanish on exit otherwise),
    and status/shutdown only see apps deployed by the same process (programmatic
    use: ray_tpu.serve.status()/shutdown() in the driver)."""
    import ray_tpu

    ray_tpu.init()
    from ray_tpu import serve

    if args.serve_cmd == "deploy":
        names = serve.apply_config_file(args.config)
        print(f"deployed: {', '.join(names)}", flush=True)
        if args.no_block:
            print("warning: --no-block exits immediately and tears the apps down "
                  "(in-process runtime)", file=sys.stderr)
            return 0
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        return 0
    if args.serve_cmd == "status":
        st = serve.status()
        if not st:
            print("no apps in this process (serve runs in the deploying process; "
                  "use ray_tpu.serve.status() in the driver)", file=sys.stderr)
        print(json.dumps(st, indent=2, default=str))
        return 0
    if args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve shut down (this process's session)")
        return 0
    return 2


def cmd_job(args) -> int:
    mgr = JobManager()
    if args.job_cmd == "submit":
        entry = args.entrypoint
        job_id = mgr.submit_job(entry)
        print(job_id)
        if not args.no_wait:
            status = mgr.wait_job(job_id)
            print(mgr.get_job_logs(job_id), end="")
            return 0 if status == "SUCCEEDED" else 1
        return 0
    if args.job_cmd == "list":
        for info in mgr.list_jobs():
            print(f"{info.job_id}\t{info.status}\t{info.entrypoint}")
        return 0
    if args.job_cmd == "status":
        print(mgr.get_job_status(args.job_id))
        return 0
    if args.job_cmd == "logs":
        print(mgr.get_job_logs(args.job_id), end="")
        return 0
    if args.job_cmd == "stop":
        print("stopped" if mgr.stop_job(args.job_id) else "not running")
        return 0
    return 2


def cmd_list(args) -> int:
    """`ray-tpu list nodes|workers|tasks|actors|objects|placement-groups|config`
    (reference `ray list ...`, python/ray/util/state/state_cli.py). Runs against
    the in-process cluster, or a remote head via --address; `config` prints the
    central flag registry (reference ray_config_def.h) and needs no cluster."""
    import ray_tpu

    if args.resource == "config":
        from ray_tpu.config import CONFIG

        print(CONFIG.describe())
        return 0

    if args.address:
        ray_tpu.init(address=args.address)
    elif not ray_tpu.is_initialized():
        print("no cluster: pass --address ray-tpu://host:port (or run inside a driver)")
        return 1
    from ray_tpu.util import state as rs

    fns = {
        "stacks": rs.get_worker_stacks,
        "nodes": rs.list_nodes,
        "workers": rs.list_workers,
        "tasks": rs.list_tasks,
        "actors": rs.list_actors,
        "objects": rs.list_objects,
        "placement-groups": rs.list_placement_groups,
        "summary": rs.summarize_cluster,
        "logs": rs.list_logs,
    }
    out = fns[args.resource]()
    print(json.dumps(out, indent=2, default=str))
    return 0


def cmd_metrics(args) -> int:
    """`ray-tpu metrics launch-config`: write prometheus.yml + Grafana
    provisioning under the session dir (reference `ray metrics launch-prometheus`
    / dashboard/modules/metrics provisioning)."""
    from ray_tpu.metrics_provision import provision

    root = provision(session_dir=args.session_dir or None)
    print(f"metrics configs written under {root}")
    print(f"  prometheus --config.file={root}/prometheus/prometheus.yml")
    print(f"  grafana-server --config {root}/grafana/grafana.ini")
    return 0


def cmd_profile(args) -> int:
    """`ray-tpu profile --duration 5 -o prof.json`: sampling profile of every
    worker + driver, written as a speedscope document (reference: py-spy via
    the dashboard reporter)."""
    import ray_tpu

    if args.address:
        ray_tpu.init(address=args.address)
    elif not ray_tpu.is_initialized():
        print("no cluster: pass --address ray-tpu://host:port (or run inside a driver)")
        return 1
    from ray_tpu.util import state as rs

    profs = rs.profile_workers(duration_s=args.duration, hz=args.hz)
    doc = rs.profile_to_speedscope(profs)
    with open(args.output, "w") as f:
        json.dump(doc, f)
    n = sum(len(v) for v in profs.values())
    print(f"{len(profs)} processes, {n} unique stacks -> {args.output} "
          f"(open at https://speedscope.app)")
    return 0


def cmd_up(args) -> int:
    """`ray-tpu up cluster.yaml` (reference `ray up`)."""
    import ray_tpu
    from ray_tpu.autoscaler.launcher import ClusterConfig, ClusterLauncher

    config = ClusterConfig.from_yaml(args.config)
    ray_tpu.init()
    launcher = ClusterLauncher(config)
    head = launcher.up(start_autoscaler=not args.no_autoscaler)
    print(f"cluster {config.cluster_name!r} up: head={head.instance_id}, "
          f"{len(launcher.provider.non_terminated_nodes())} node(s)")
    state = {
        "config": args.config,
        "cluster_name": config.cluster_name,
        # instance ids let a later `ray-tpu down` (fresh process) terminate
        # nodes whose provider tracks them only in memory (tpu-pod)
        "instances": [
            {"instance_id": n.instance_id, "node_type": n.node_type}
            for n in launcher.provider.non_terminated_nodes()
        ],
    }
    os.makedirs(default_session_dir(), exist_ok=True)
    with open(os.path.join(default_session_dir(), "cluster.json"), "w") as f:
        json.dump(state, f)
    if args.block:
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            launcher.down()
    return 0


def cmd_down(args) -> int:
    """`ray-tpu down [cluster.yaml]` (reference `ray down`)."""
    from ray_tpu.autoscaler.launcher import ClusterConfig, ClusterLauncher

    path = args.config
    state_file = os.path.join(default_session_dir(), "cluster.json")
    recorded = {}
    if os.path.exists(state_file):
        with open(state_file) as f:
            recorded = json.load(f)
    path = path or recorded.get("config")
    if path is None:
        print("no cluster config given and no recorded cluster")
        return 1
    config = ClusterConfig.from_yaml(path)
    launcher = ClusterLauncher(config)
    # only adopt (and clear) the recorded state if it belongs to THIS cluster —
    # `ray-tpu down other.yaml` must not terminate or forget another cluster's nodes
    same_cluster = recorded.get("cluster_name") == config.cluster_name
    if same_cluster:
        launcher.adopt(recorded.get("instances", []))
    n = launcher.down()
    if same_cluster:
        try:
            os.remove(state_file)
        except OSError:
            pass
    print(f"cluster {config.cluster_name!r} down ({n} node(s) terminated)")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("up", help="launch a cluster from a YAML config")
    sp.add_argument("config")
    sp.add_argument("--no-autoscaler", action="store_true")
    sp.add_argument("--block", action="store_true")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down a launched cluster")
    sp.add_argument("config", nargs="?", default=None)
    sp.set_defaults(fn=cmd_down)

    sp = sub.add_parser("list", help="state API listings (reference `ray list`)")
    sp.add_argument("resource", choices=["nodes", "workers", "tasks", "actors",
                                         "objects", "placement-groups", "summary",
                                         "stacks", "config", "logs"])
    sp.add_argument("--address", default=None,
                    help="connect as a client driver, e.g. ray-tpu://127.0.0.1:10001")
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("start", help="record head session (optionally --block with dashboard), "
                                      "or --address=HOST:PORT to join a head as a node agent")
    sp.add_argument("--address", default=None,
                    help="join an existing head's node server as this host's agent")
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--dashboard-port", type=int, default=None,
                    help="default: CONFIG.dashboard_port (RAY_TPU_DASHBOARD_PORT)")
    sp.add_argument("--node-server-port", type=int, default=None,
                    help="accept node agents on this port (0 = ephemeral; head only)")
    sp.add_argument("--node-server-host", default="127.0.0.1")
    sp.add_argument("--block", action="store_true")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="clear head session")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("tls-init", help="mint a self-signed cluster CA + cert "
                        "(then set RAY_TPU_USE_TLS + RAY_TPU_TLS_* and "
                        "distribute the files to every node)")
    sp.add_argument("dir", help="output directory for ca.crt/cluster.crt/cluster.key")
    sp.add_argument("--san", action="append", default=[],
                    help="extra SAN entry (IP or DNS name; repeatable)")
    sp.set_defaults(fn=cmd_tls_init)

    sp = sub.add_parser("metrics", help="metrics plane provisioning")
    sp.add_argument("action", choices=["launch-config"])
    sp.add_argument("--session-dir", default="")
    sp.set_defaults(fn=cmd_metrics)

    sp = sub.add_parser("profile", help="sampling profile -> speedscope json")
    sp.add_argument("--address", default="")
    sp.add_argument("--duration", type=float, default=5.0)
    sp.add_argument("--hz", type=float, default=100.0)
    sp.add_argument("-o", "--output", default="ray_tpu_profile.json")
    sp.set_defaults(fn=cmd_profile)

    sp = sub.add_parser("status", help="show head session + live load summary "
                        "(transfer GB/s, collective ops/aborts, serve TTFT, "
                        "train MFU); --watch adds history sparklines + SLOs")
    sp.add_argument("--address", default=None,
                    help="connect as a client driver for the live summary, "
                         "e.g. ray-tpu://127.0.0.1:10001")
    sp.add_argument("--watch", action="store_true",
                    help="re-render every --interval seconds with "
                         "metrics-history sparklines and SLO burn state")
    sp.add_argument("--interval", type=float, default=3.0)
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("trace", help="render one request's critical path "
                        "(span tree + queue/prefill/decode/transfer/other "
                        "attribution) from its trace id")
    sp.add_argument("trace_id", help="32-hex trace id (from the serve "
                    "ingress's traceparent response header)")
    sp.add_argument("--address", default=None,
                    help="connect as a client driver, e.g. ray-tpu://127.0.0.1:10001")
    sp.add_argument("--json", action="store_true",
                    help="print the raw state.request_trace document")
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser("lint", help="graftlint: AST project-invariant "
                        "analysis (swallowed errors, hot-path host syncs, "
                        "blocking control paths, knob registry, thread "
                        "hygiene, no-print)")
    sp.add_argument("lint_args", nargs="*", metavar="path",
                    help="subdirs/files to lint (default: ray_tpu)")
    sp.add_argument("--write-docs", action="store_true",
                    help="regenerate README knob tables from ray_tpu/knobs.py")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--show-allowed", action="store_true")
    sp.set_defaults(fn=cmd_lint)

    sp = sub.add_parser("submit", help="run a python script as a job")
    sp.add_argument("script")
    sp.add_argument("script_args", nargs="*")
    sp.set_defaults(fn=cmd_submit)

    sp = sub.add_parser("serve", help="serve deploy/status/shutdown")
    ssub = sp.add_subparsers(dest="serve_cmd", required=True)
    s = ssub.add_parser("deploy")
    s.add_argument("config")
    s.add_argument("--no-block", action="store_true")
    ssub.add_parser("status")
    ssub.add_parser("shutdown")
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("job", help="job management")
    jsub = sp.add_subparsers(dest="job_cmd", required=True)
    j = jsub.add_parser("submit")
    j.add_argument("--no-wait", action="store_true")
    j.add_argument("entrypoint")
    j = jsub.add_parser("list")
    j = jsub.add_parser("status")
    j.add_argument("job_id")
    j = jsub.add_parser("logs")
    j.add_argument("job_id")
    j = jsub.add_parser("stop")
    j.add_argument("job_id")
    sp.set_defaults(fn=cmd_job)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
