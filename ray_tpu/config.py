"""Central flag registry: every RAY_TPU_* knob, typed and documented.

Capability parity: reference src/ray/common/ray_config_def.h (the RAY_CONFIG
X-macro registry, 219 entries, env-overridable as RAY_<name>) — one place to
see every flag, its type, default, and where its current value came from.
`ray-tpu list config` prints the table.

Values are read from the environment AT ACCESS TIME (so tests can monkeypatch
and long-lived processes can be reconfigured between runs) and fall back to the
documented default. Worker-plumbing variables the runtime sets for its own
children (RAY_TPU_ARENA, RAY_TPU_TRAIN_RANK, ...) are internal protocol, not
operator flags, and are deliberately not listed here.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class Flag:
    name: str  # attribute name on CONFIG
    env: str  # environment variable that overrides it
    type: str  # "int" | "float" | "bool" | "str"
    default: Any  # None = unset/auto
    doc: str

    def parse(self, raw: str) -> Any:
        if self.type == "int":
            return int(raw)
        if self.type == "float":
            return float(raw)
        if self.type == "bool":
            return raw.strip().lower() in ("1", "true", "yes", "on")
        return raw


_FLAGS: List[Flag] = [
    # -- resources / topology
    Flag("num_cpus", "RAY_TPU_NUM_CPUS", "float", None,
         "CPU capacity this node advertises (default: os.cpu_count())."),
    Flag("num_tpus", "RAY_TPU_NUM_TPUS", "float", None,
         "TPU chip capacity this node advertises (default: auto-detect)."),
    Flag("max_workers_per_node", "RAY_TPU_MAX_WORKERS_PER_NODE", "int", 16,
         "Worker-process cap per node (reference: raylet worker pool size)."),
    # -- object store / memory
    Flag("object_store_bytes", "RAY_TPU_OBJECT_STORE_BYTES", "int", 512 * 1024 * 1024,
         "Shared-memory arena capacity per node (plasma-equivalent)."),
    Flag("spill_dir", "RAY_TPU_SPILL_DIR", "str", "/tmp",
         "Directory for objects spilled from shared memory to disk."),
    Flag("spill_threshold", "RAY_TPU_SPILL_THRESHOLD", "float", 0.8,
         "Arena-usage fraction above which LRU spilling starts."),
    Flag("spill_target", "RAY_TPU_SPILL_TARGET", "float", 0.5,
         "Arena-usage fraction spilling drives down to."),
    Flag("memory_usage_threshold", "RAY_TPU_MEMORY_USAGE_THRESHOLD", "float", 0.95,
         "System-memory fraction that triggers the OOM worker killer "
         "(reference memory_monitor.h)."),
    Flag("memory_monitor_refresh_ms", "RAY_TPU_MEMORY_MONITOR_REFRESH_MS", "int", 250,
         "Memory monitor / spill check period."),
    Flag("inline_threshold_bytes", "RAY_TPU_INLINE_THRESHOLD_BYTES", "int", 100 * 1024,
         "Objects below this travel inline in control messages instead of the "
         "arena (reference max_direct_call_object_size)."),
    Flag("oob_threshold_bytes", "RAY_TPU_OOB_THRESHOLD_BYTES", "int", 1 << 16,
         "Pickle buffers at or above this serialize out-of-band (zero-copy "
         "into the arena) instead of inline in the pickle stream."),
    Flag("object_location_timeout_s", "RAY_TPU_OBJECT_LOCATION_TIMEOUT_S",
         "float", 60.0,
         "How long a get() waits for a recovering object's new location "
         "after lineage resubmission before failing."),
    Flag("localize_pull_timeout_s", "RAY_TPU_LOCALIZE_PULL_TIMEOUT_S",
         "float", 120.0,
         "Deadline for pulling a task's missing arguments to its assigned "
         "node; expiry triggers lineage reconstruction or task failure."),
    Flag("task_max_retries", "RAY_TPU_TASK_MAX_RETRIES", "int", 3,
         "Default max_retries for @remote tasks when unspecified "
         "(reference task_max_retries / TASK_MAX_RETRIES default)."),
    Flag("actor_max_restarts", "RAY_TPU_ACTOR_MAX_RESTARTS", "int", 0,
         "Default max_restarts for actors when unspecified (reference "
         "actor restart semantics: 0 = never restart)."),
    Flag("worker_start_timeout_s", "RAY_TPU_WORKER_START_TIMEOUT_S", "float", 60.0,
         "How long the pool waits for a spawned worker's handshake "
         "(reference worker_register_timeout_seconds)."),
    Flag("metrics_report_interval_s", "RAY_TPU_METRICS_REPORT_INTERVAL_S", "float", 2.0,
         "Worker metric-snapshot push period to the head "
         "(reference metrics_report_interval_ms)."),
    # -- multi-host control plane
    Flag("agent_heartbeat_s", "RAY_TPU_AGENT_HEARTBEAT_S", "float", 2.0,
         "Node-agent heartbeat period to the head."),
    Flag("agent_batch_max", "RAY_TPU_AGENT_BATCH_MAX", "int", 128,
         "Max frames coalesced into one gRPC agent-stream message (batching "
         "packs only already-queued frames: zero added latency)."),
    Flag("agent_queue_depth", "RAY_TPU_AGENT_QUEUE_DEPTH", "int", 4096,
         "Outbound frame buffer per agent stream; a stalled peer exerts "
         "backpressure once full instead of accumulating frames in RAM."),
    Flag("agent_send_timeout_s", "RAY_TPU_AGENT_SEND_TIMEOUT_S", "float", 30.0,
         "How long send() blocks on a backed-up agent stream before raising."),
    Flag("tls_handshake_timeout_s", "RAY_TPU_TLS_HANDSHAKE_TIMEOUT_S", "float",
         15.0, "Deferred server-side TLS handshake deadline per connection."),
    Flag("collective_op_timeout_s", "RAY_TPU_COLLECTIVE_OP_TIMEOUT_S", "float",
         30.0, "Host-plane collective op timeout (allreduce/broadcast/...); "
         "barriers wait 2x this."),
    Flag("collective_abort_poll_interval_s",
         "RAY_TPU_COLLECTIVE_ABORT_POLL_INTERVAL_S", "float", 0.25,
         "How often ring-path collective waits (stream reduce, gathers, tree "
         "relays) probe the group coordinator's abort poison flag: a dead "
         "rank costs survivors one interval, not collective_op_timeout_s."),
    # -- transport security
    Flag("use_tls", "RAY_TPU_USE_TLS", "bool", False,
         "mTLS on the gRPC agent channel and the data/device-plane listeners; "
         "plaintext peers are refused (reference tls_utils.py RAY_USE_TLS)."),
    Flag("tls_ca", "RAY_TPU_TLS_CA", "str", None,
         "CA certificate path (both trust root and client-auth verifier)."),
    Flag("tls_cert", "RAY_TPU_TLS_CERT", "str", None,
         "Cluster certificate path (`ray-tpu tls-init` mints one)."),
    Flag("tls_key", "RAY_TPU_TLS_KEY", "str", None,
         "Cluster private key path."),
    Flag("container_runtime", "RAY_TPU_CONTAINER_RUNTIME", "str", None,
         "Container launcher binary for container/image_uri runtime envs "
         "(default: docker, then podman, from PATH). Point it at a recording "
         "stub to test invocations without a real runtime."),
    Flag("serve_ingress_tls", "RAY_TPU_SERVE_INGRESS_TLS", "bool", False,
         "Serve the HTTP and gRPC ingress proxies over TLS using the cluster "
         "certificate (server-side TLS: external clients verify against "
         "ca.crt but need no client cert, unlike the inter-node mTLS planes)."),
    Flag("pd_export_ttl_s", "RAY_TPU_PD_EXPORT_TTL_S", "float", 600.0,
         "Device-plane auto-release backstop for P/D prefill KV exports whose "
         "decode consumer crashed before acking."),
    Flag("pd_export_max_live", "RAY_TPU_PD_EXPORT_MAX_LIVE", "int", 128,
         "Max un-acked P/D KV exports a prefill engine pins before LRU "
         "pruning (each pins device memory until the decode side pulls)."),
    Flag("llm_engine_idle_wait_s", "RAY_TPU_LLM_ENGINE_IDLE_WAIT_S", "float",
         0.05, "Engine scheduler-loop sleep when no slot is active (admission "
         "latency floor for the first request of a burst)."),
    Flag("moe_group_size", "RAY_TPU_MOE_GROUP_SIZE", "int", 4096,
         "Tokens per MoE dispatch group: dispatch/combine tensors are "
         "[group, experts, capacity], so memory is O(tokens x group)."),
    Flag("serve_reconcile_interval_s", "RAY_TPU_SERVE_RECONCILE_INTERVAL_S",
         "float", 0.2, "Serve controller reconciliation loop period (replica "
         "create/kill, health checks, autoscale decisions)."),
    # -- device plane (device-to-device tensor transfer between processes)
    Flag("device_plane", "RAY_TPU_DEVICE_PLANE", "bool", True,
         "Enable the PJRT transfer-server plane: jax.Arrays move between actor "
         "processes device-to-device (DCN/ICI on pods) instead of "
         "device->host->pickle (reference gpu_object_manager + NCCL channels)."),
    Flag("device_objects", "RAY_TPU_DEVICE_OBJECTS", "str", "fetch",
         "jax.Arrays in the object store: 'off' = host copy only; 'fetch' "
         "(default) = host copy kept, consumers pull device-to-device when "
         "possible; 'native' = stub only, device-resident at the producer "
         "(reference gpu_object_manager semantics: loss -> reconstruction)."),
    Flag("device_object_min_bytes", "RAY_TPU_DEVICE_OBJECT_MIN_BYTES", "int", 1 << 20,
         "Device arrays below this size skip the transfer plane (control-message "
         "inlining beats an arm round-trip for small tensors)."),
    # -- data plane (direct node-to-node object transfer)
    Flag("transfer_chunk_bytes", "RAY_TPU_TRANSFER_CHUNK_BYTES", "int", 4 * 1024 * 1024,
         "Chunk size for direct node-to-node object transfers "
         "(reference push_manager.h chunked push)."),
    Flag("transfer_inflight_bytes", "RAY_TPU_TRANSFER_INFLIGHT_BYTES", "int",
         256 * 1024 * 1024,
         "Per-node byte budget for concurrent incoming object pulls "
         "(reference pull_manager.h admission control)."),
    Flag("transfer_max_pulls", "RAY_TPU_TRANSFER_MAX_PULLS", "int", 8,
         "Max concurrent pulls a node issues (and streams it serves)."),
    Flag("transfer_stripe_threshold_bytes",
         "RAY_TPU_TRANSFER_STRIPE_THRESHOLD_BYTES", "int", 8 * 1024 * 1024,
         "Objects at or above this size pull as concurrent byte-range stripes "
         "over pooled connections (0 disables striping). All stripes of one "
         "pull share a single admission grant."),
    Flag("transfer_stripes", "RAY_TPU_TRANSFER_STRIPES", "int", 4,
         "Max concurrent range streams per striped pull."),
    Flag("transfer_stripe_min_bytes", "RAY_TPU_TRANSFER_STRIPE_MIN_BYTES",
         "int", 2 * 1024 * 1024,
         "Never split a pull so finely that a stripe falls below this many "
         "bytes (each stripe pays a request/admission handshake)."),
    Flag("transfer_same_host_map", "RAY_TPU_TRANSFER_SAME_HOST_MAP", "bool",
         True,
         "When the source's shm/arena/spill location is directly readable "
         "from the pulling process (source shares this machine's /dev/shm — "
         "colocated node processes), map it in place instead of copying the "
         "bytes over loopback TCP (reference: one plasma store per node). "
         "The striped wire path is for genuinely-remote peers."),
    Flag("transfer_timeout_s", "RAY_TPU_TRANSFER_TIMEOUT_S", "float", 300.0,
         "Deadline for one direct object transfer before head-relay fallback."),
    Flag("transfer_stall_timeout_s", "RAY_TPU_TRANSFER_STALL_TIMEOUT_S", "float", 60.0,
         "Per-socket-op stall bound on data-plane transfers (a half-dead peer "
         "must not pin admission slots / puller threads forever)."),
    Flag("collective_ring_threshold_bytes", "RAY_TPU_COLLECTIVE_RING_THRESHOLD_BYTES",
         "int", 64 * 1024,
         "SHM-collective payloads at or above this size move peer-to-peer over "
         "the data plane (ring path, coordinator carries metadata only); "
         "smaller payloads ride the coordinator board directly."),
    Flag("collective_server_streams", "RAY_TPU_COLLECTIVE_SERVER_STREAMS", "int", 64,
         "Concurrent serve streams on a rank's collective data-plane server. "
         "Ring reads block until the local chunk is published, so this is "
         "sized above transfer_max_pulls to keep blocked readers from "
         "starving live ones."),
    Flag("agent_heartbeat_timeout_s", "RAY_TPU_AGENT_HEARTBEAT_TIMEOUT_S", "float", 10.0,
         "Head marks an agent dead after this long without a heartbeat "
         "(reference gcs_health_check_manager.h)."),
    Flag("agent_reconnect_timeout_s", "RAY_TPU_AGENT_RECONNECT_TIMEOUT_S", "float", 60.0,
         "How long a node agent keeps its workers alive while redialing a "
         "restarted head before giving up (reference: raylets buffering "
         "through a GCS restart, NotifyGCSRestart)."),
    # -- session / auth
    Flag("session_dir", "RAY_TPU_SESSION_DIR", "str", "/tmp/ray_tpu_session",
         "Session directory (head metadata, jobs, authkey, usage report)."),
    Flag("client_authkey", "RAY_TPU_CLIENT_AUTHKEY", "str", None,
         "Cluster authkey for remote drivers/agents (default: generated and "
         "persisted in the session dir)."),
    Flag("gcs_persistence_path", "RAY_TPU_GCS_PERSISTENCE_PATH", "str", None,
         "Journal file for GCS KV persistence across restarts (default: off)."),
    Flag("gcs_owner_check_every", "RAY_TPU_GCS_OWNER_CHECK_EVERY", "int", 32,
         "URI-journal split-brain fencing: re-verify lease ownership every N "
         "appends (lower = faster usurper detection, more object reads)."),
    Flag("job_stop_grace_s", "RAY_TPU_JOB_STOP_GRACE_S", "float", 5.0,
         "SIGTERM-to-SIGKILL grace when stopping a submitted job's process "
         "group (reference: job stop_timeout)."),
    Flag("dag_channel_buffer_bytes", "RAY_TPU_DAG_CHANNEL_BUFFER_BYTES", "int",
         4 * 1024 * 1024,
         "Default seqlock shm channel capacity for compiled DAGs "
         "(experimental_compile buffer_size_bytes; reference "
         "ChannelContext buffer sizing)."),
    # -- ops (kernel tiling; trace-time reads, safe to tune per-run)
    Flag("flash_block_q", "RAY_TPU_FLASH_BLOCK_Q", "int", 512,
         "Pallas flash-attention query-tile rows (MXU-aligned multiple of 8; "
         "512 saturates v5e at head_dim 64-128)."),
    Flag("flash_block_kv", "RAY_TPU_FLASH_BLOCK_KV", "int", 512,
         "Pallas flash-attention key/value-tile rows."),
    Flag("chunked_attention_min_logits", "RAY_TPU_CHUNKED_ATTENTION_MIN_LOGITS",
         "int", 1 << 20,
         "Sq*Skv above which non-pallas attention switches to the chunked "
         "online-softmax path (bounds the logits buffer on long context)."),
    Flag("tqdm_render_interval_s", "RAY_TPU_TQDM_RENDER_INTERVAL_S", "float",
         0.1, "Min seconds between driver-side tqdm_ray re-renders."),
    # -- observability
    Flag("tracing", "RAY_TPU_TRACING", "bool", False,
         "Enable OpenTelemetry-style span recording AND the hot-path "
         "telemetry event recorder (util/telemetry.py) at init."),
    Flag("telemetry_ring_size", "RAY_TPU_TELEMETRY_RING_SIZE", "int", 8192,
         "Per-process telemetry ring-buffer capacity (events). Overflow drops "
         "the oldest events and logs a throttled warning at flush."),
    Flag("metrics_scrape_interval_s", "RAY_TPU_METRICS_SCRAPE_INTERVAL_S",
         "float", 5.0,
         "Head-side metrics-history scrape period: the merged cross-worker "
         "snapshot is sampled into a timestamped frame ring this often, "
         "feeding windowed rates/quantiles and the SLO engine. 0 disables "
         "the scraper."),
    Flag("metrics_history_size", "RAY_TPU_METRICS_HISTORY_SIZE", "int", 360,
         "Frames retained in the metrics-history ring (at the default 5 s "
         "scrape interval, 360 frames = 30 min of windowed history)."),
    Flag("usage_stats", "RAY_TPU_USAGE_STATS", "bool", False,
         "Record a local-only feature-usage summary in the session dir "
         "(never leaves the machine)."),
    Flag("lp_debug", "RAY_TPU_LP_DEBUG", "bool", False,
         "Verbose serve long-poll client logging."),
    Flag("dashboard_port", "RAY_TPU_DASHBOARD_PORT", "int", 8265,
         "Dashboard HTTP port (JSON API, /metrics exposition, web UI)."),
    # -- autoscaler / provisioning
    Flag("provision_max_attempts", "RAY_TPU_PROVISION_MAX_ATTEMPTS", "int", 4,
         "Inline create_node attempts for rate-limit/transient cloud errors "
         "before the failure escalates to the autoscaler backoff (reference "
         "gcp node.py retry loops)."),
    Flag("provision_backoff_s", "RAY_TPU_PROVISION_BACKOFF_S", "float", 2.0,
         "Base for the jittered exponential inline-retry backoff in "
         "create_node."),
    Flag("launch_backoff_max_s", "RAY_TPU_LAUNCH_BACKOFF_MAX_S", "float", 600.0,
         "Cap on the autoscaler's per-node-type launch backoff after "
         "quota/stockout/permanent provision failures."),
    # -- data (DataContext defaults; per-driver overrides via DataContext)
    Flag("data_max_inflight_tasks_per_op", "RAY_TPU_DATA_MAX_INFLIGHT_TASKS_PER_OP",
         "int", 8,
         "Streaming-executor backpressure: tasks in flight per operator "
         "(reference backpressure_policy concurrency caps)."),
    Flag("data_actor_pool_max_size", "RAY_TPU_DATA_ACTOR_POOL_MAX_SIZE", "int", 4,
         "Default actor-pool size for map_batches(Class) stages."),
    Flag("data_read_op_min_num_blocks", "RAY_TPU_DATA_READ_OP_MIN_NUM_BLOCKS",
         "int", 8,
         "Default read parallelism when the datasource does not dictate one."),
    Flag("data_target_max_block_size", "RAY_TPU_DATA_TARGET_MAX_BLOCK_SIZE",
         "int", 128 * 1024 * 1024,
         "Blocks above this split on output (reference target_max_block_size)."),
    Flag("data_target_min_block_size", "RAY_TPU_DATA_TARGET_MIN_BLOCK_SIZE",
         "int", 1 * 1024 * 1024,
         "Coalesce blocks below this (reference target_min_block_size)."),
    Flag("data_default_batch_size", "RAY_TPU_DATA_DEFAULT_BATCH_SIZE", "int", 1024,
         "map_batches/iter_batches batch size when unspecified."),
    Flag("data_op_output_buffer_limit", "RAY_TPU_DATA_OP_OUTPUT_BUFFER_LIMIT",
         "int", 16,
         "Streaming-executor per-operator output queue cap (backpressure)."),
    Flag("data_push_based_shuffle", "RAY_TPU_DATA_PUSH_BASED_SHUFFLE", "bool", False,
         "Staged-merge shuffle for large sorts (reference "
         "push_based_shuffle_task_scheduler; RAY_DATA_PUSH_BASED_SHUFFLE)."),
    Flag("data_push_shuffle_merge_factor", "RAY_TPU_DATA_PUSH_SHUFFLE_MERGE_FACTOR",
         "int", 8,
         "Map-round width for the push-based shuffle (fan-in bound)."),
    # -- serve
    Flag("serve_replica_wait_s", "RAY_TPU_SERVE_REPLICA_WAIT_S", "float", 30.0,
         "How long a handle call waits for a live replica before failing "
         "(reference handle resolution timeout)."),
    Flag("serve_health_check_period_s", "RAY_TPU_SERVE_HEALTH_CHECK_PERIOD_S",
         "float", 5.0,
         "Default replica health-check period (per-deployment override in "
         "DeploymentConfig; reference health_check_period_s)."),
    Flag("serve_health_check_timeout_s", "RAY_TPU_SERVE_HEALTH_CHECK_TIMEOUT_S",
         "float", 10.0,
         "Default grace before an unresponsive replica is replaced "
         "(reference health_check_timeout_s)."),
    Flag("serve_max_ongoing_requests", "RAY_TPU_SERVE_MAX_ONGOING_REQUESTS",
         "int", 8,
         "Default per-replica concurrent-request cap "
         "(reference max_ongoing_requests)."),
    Flag("serve_max_queued_requests", "RAY_TPU_SERVE_MAX_QUEUED_REQUESTS",
         "int", -1,
         "Default per-deployment queue cap beyond replica capacity "
         "(max_ongoing_requests x replicas): excess handle calls are shed "
         "with BackPressureError / HTTP 503 + Retry-After instead of "
         "queueing into latency collapse. -1 = unbounded (no shedding)."),
    Flag("serve_request_retries", "RAY_TPU_SERVE_REQUEST_RETRIES", "int", 3,
         "Max times a handle call is re-sent to a DIFFERENT replica after a "
         "replica-death/unavailable failure (deployments with "
         "retryable=False never retry). User-code exceptions never retry."),
    Flag("serve_retry_backoff_s", "RAY_TPU_SERVE_RETRY_BACKOFF_S", "float",
         0.05,
         "Base of the jittered exponential backoff between serve request "
         "retries (attempt N sleeps ~base*2^(N-1), capped)."),
    Flag("serve_retry_backoff_max_s", "RAY_TPU_SERVE_RETRY_BACKOFF_MAX_S",
         "float", 2.0,
         "Cap on the serve request retry backoff."),
    Flag("serve_suspect_ttl_s", "RAY_TPU_SERVE_SUSPECT_TTL_S", "float", 30.0,
         "How long the handle router excludes a replica after a "
         "replica-death classified failure (the suspect list bridges the gap "
         "until the controller's health check removes it from the long-poll "
         "view)."),
    Flag("serve_drain_timeout_s", "RAY_TPU_SERVE_DRAIN_TIMEOUT_S", "float",
         30.0,
         "Default grace a DRAINING replica gets to finish in-flight requests "
         "on scale-down/rolling update/shutdown before it is killed anyway "
         "(per-deployment override: drain_timeout_s)."),
    Flag("fault_injection", "RAY_TPU_FAULT_INJECTION", "str", None,
         "Arm util/fault_injection.py fail points from the environment: "
         "'site=mode[@p=0.5][@n=3][@delay=0.1][@seed=7][;site2=...]' with "
         "mode error|delay|kill. Deterministic chaos for tests/drills; "
         "unset = every fail point is a no-op."),
    # -- llm engine defaults
    Flag("llm_max_num_seqs", "RAY_TPU_LLM_MAX_NUM_SEQS", "int", 8,
         "Default decode-slot count for LLMConfig (continuous batching width)."),
    Flag("llm_max_model_len", "RAY_TPU_LLM_MAX_MODEL_LEN", "int", 1024,
         "Default per-slot KV capacity for LLMConfig."),
    Flag("llm_fused_steps", "RAY_TPU_LLM_FUSED_STEPS", "int", 0,
         "Default fused decode burst width when LLMConfig.num_decode_steps is "
         "unset: the engine runs this many decode+sample steps on device per "
         "host sync. 0 = auto-tune from the measured host round trip vs the "
         "measured device step time."),
    Flag("llm_fused_steps_max", "RAY_TPU_LLM_FUSED_STEPS_MAX", "int", 32,
         "Upper bound for the auto-tuned fused decode burst width (bounds "
         "both K-token streaming granularity and the log2(K) compiled decode "
         "program count)."),
    Flag("llm_fused_sync_target", "RAY_TPU_LLM_FUSED_SYNC_TARGET", "float",
         0.15,
         "Auto-tune target for the host-sync share of a decode burst: K is "
         "raised until host_round_trip/(host_round_trip + K*device_step) "
         "drops to this fraction (subject to llm_fused_steps_max)."),
    Flag("llm_prefix_min_hit_tokens", "RAY_TPU_LLM_PREFIX_MIN_HIT_TOKENS",
         "int", 0,
         "Prefix-cache pay-or-skip floor: a warm prefill only uses the cache "
         "when the cached-token count reaches this. 0 = auto — skip when the "
         "predicted compute saving (hit tokens x measured per-token prefill "
         "time) is below the measured dispatch round trip."),
    # -- train
    Flag("train_v2_enabled", "RAY_TPU_TRAIN_V2_ENABLED", "bool", False,
         "Route trainers through the v2 controller (FailurePolicy/"
         "ScalingPolicy; reference RAY_TRAIN_V2_ENABLED)."),
    Flag("train_restart_backoff_s", "RAY_TPU_TRAIN_RESTART_BACKOFF_S",
         "float", 1.0,
         "Base of the bounded exponential backoff between Train worker-group "
         "restarts (failure N sleeps base*2^(N-1), capped). 0 disables."),
    Flag("train_restart_backoff_max_s", "RAY_TPU_TRAIN_RESTART_BACKOFF_MAX_S",
         "float", 30.0,
         "Cap on the Train restart backoff."),
    Flag("storage_path", "RAY_TPU_STORAGE_PATH", "str", None,
         "Default experiment storage path (default: ~/ray_tpu_results)."),
]

_BY_NAME: Dict[str, Flag] = {f.name: f for f in _FLAGS}


def flag(name: str) -> Any:
    """Current value of a registry flag — THE accessor for dataclass
    default_factory lambdas (DataContext, DeploymentConfig, LLMConfig)."""
    return getattr(CONFIG, name)


class _Config:
    """Attribute access returns the flag's current (env-overridden) value."""

    def __getattr__(self, name: str) -> Any:
        flag = _BY_NAME.get(name)
        if flag is None:
            raise AttributeError(f"unknown ray_tpu config flag {name!r}")
        raw = os.environ.get(flag.env)
        if raw is None or raw == "":
            return flag.default
        return flag.parse(raw)

    @staticmethod
    def flags() -> List[Flag]:
        return list(_FLAGS)

    @staticmethod
    def entries() -> List[Dict[str, Any]]:
        """Current value + provenance for every flag (`ray-tpu list config`)."""
        out = []
        for f in _FLAGS:
            raw = os.environ.get(f.env)
            overridden = raw is not None and raw != ""
            out.append({
                "name": f.name,
                "env": f.env,
                "type": f.type,
                "value": f.parse(raw) if overridden else f.default,
                "source": "env" if overridden else "default",
                "doc": f.doc,
            })
        return out

    @staticmethod
    def describe() -> str:
        rows = _Config.entries()
        w = max(len(r["env"]) for r in rows)
        lines = []
        for r in rows:
            lines.append(f"{r['env']:<{w}}  {str(r['value']):<12} [{r['source']:<7}] "
                         f"({r['type']}) {r['doc']}")
        return "\n".join(lines)


CONFIG = _Config()


def memoized_flag(name: str):
    """A zero-arg reader for flag `name`, memoized against the raw env string.

    For HOT paths only (per-put / per-serialize / per-render): env changes
    still apply live, but the parse + registry lookup (~1.7us through
    CONFIG.__getattr__) is paid once per env-string change (~0.1us after).
    Everything else should read CONFIG.<name> directly."""
    f = _BY_NAME[name]
    memo = [object(), None]  # sentinel: first call always parses

    def read() -> Any:
        raw = os.environ.get(f.env)
        if raw == memo[0]:
            return memo[1]
        val = f.default if raw is None or raw == "" else f.parse(raw)
        memo[0], memo[1] = raw, val
        return val

    return read
