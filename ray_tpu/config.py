"""CONFIG: typed, env-overridable accessors over the central knob registry.

Capability parity: reference src/ray/common/ray_config_def.h (the RAY_CONFIG
X-macro registry, 219 entries, env-overridable as RAY_<name>) — one place to
see every flag, its type, default, and where its current value came from.
`ray-tpu list config` prints the table.

The registry itself lives in `ray_tpu.knobs` (every RAY_TPU_* knob with its
owning subsystem; graftlint enforces coverage and generates the README knob
tables from it). This module builds the CONFIG attribute table from the
registry entries that carry an `attr` — the operator-facing flags; env-only
worker knobs and internal worker-plumbing variables stay registry-only.

Values are read from the environment AT ACCESS TIME (so tests can monkeypatch
and long-lived processes can be reconfigured between runs) and fall back to
the documented default.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List

from ray_tpu.knobs import KNOBS


@dataclasses.dataclass(frozen=True)
class Flag:
    name: str  # attribute name on CONFIG
    env: str  # environment variable that overrides it
    type: str  # "int" | "float" | "bool" | "str"
    default: Any  # None = unset/auto
    doc: str

    def parse(self, raw: str) -> Any:
        if self.type == "int":
            return int(raw)
        if self.type == "float":
            return float(raw)
        if self.type == "bool":
            return raw.strip().lower() in ("1", "true", "yes", "on")
        return raw


_FLAGS: List[Flag] = [
    Flag(k.attr, k.env, k.type, k.default, k.doc)
    for k in KNOBS if k.attr is not None
]

_BY_NAME: Dict[str, Flag] = {f.name: f for f in _FLAGS}


def flag(name: str) -> Any:
    """Current value of a registry flag — THE accessor for dataclass
    default_factory lambdas (DataContext, DeploymentConfig, LLMConfig)."""
    return getattr(CONFIG, name)


class _Config:
    """Attribute access returns the flag's current (env-overridden) value."""

    def __getattr__(self, name: str) -> Any:
        flag = _BY_NAME.get(name)
        if flag is None:
            raise AttributeError(f"unknown ray_tpu config flag {name!r}")
        raw = os.environ.get(flag.env)
        if raw is None or raw == "":
            return flag.default
        return flag.parse(raw)

    @staticmethod
    def flags() -> List[Flag]:
        return list(_FLAGS)

    @staticmethod
    def entries() -> List[Dict[str, Any]]:
        """Current value + provenance for every flag (`ray-tpu list config`)."""
        out = []
        for f in _FLAGS:
            raw = os.environ.get(f.env)
            overridden = raw is not None and raw != ""
            out.append({
                "name": f.name,
                "env": f.env,
                "type": f.type,
                "value": f.parse(raw) if overridden else f.default,
                "source": "env" if overridden else "default",
                "doc": f.doc,
            })
        return out

    @staticmethod
    def describe() -> str:
        rows = _Config.entries()
        w = max(len(r["env"]) for r in rows)
        lines = []
        for r in rows:
            lines.append(f"{r['env']:<{w}}  {str(r['value']):<12} [{r['source']:<7}] "
                         f"({r['type']}) {r['doc']}")
        return "\n".join(lines)


CONFIG = _Config()


def memoized_flag(name: str):
    """A zero-arg reader for flag `name`, memoized against the raw env string.

    For HOT paths only (per-put / per-serialize / per-render): env changes
    still apply live, but the parse + registry lookup (~1.7us through
    CONFIG.__getattr__) is paid once per env-string change (~0.1us after).
    Everything else should read CONFIG.<name> directly."""
    f = _BY_NAME[name]
    memo = [object(), None]  # sentinel: first call always parses

    def read() -> Any:
        raw = os.environ.get(f.env)
        if raw == memo[0]:
            return memo[1]
        val = f.default if raw is None or raw == "" else f.parse(raw)
        memo[0], memo[1] = raw, val
        return val

    return read
